"""Anti-entropy StateSyncer: paced full + triggered partial sync.

The reference's agent/ae/ae.go:54 StateSyncer drives local.State syncs:
a full sync every SyncFull interval scaled by cluster size
(scaleFactor :35 — log2(N/128)+1 above 128 nodes) with ±stagger, and a
partial SyncChanges whenever a local mutation fires the trigger channel,
debounced and retried on failure (retryFailInterval).  Same machine here
with a condition-variable trigger instead of a channel.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Callable, Optional

from consul_tpu import telemetry

SCALE_THRESHOLD = 128          # ae.go:27 scaleThreshold
DEFAULT_SYNC_INTERVAL = 60.0   # config SyncFrequency equivalent
RETRY_FAIL_INTERVAL = 15.0     # ae.go retryFailInterval


def scale_factor(nodes: int) -> int:
    """ae.go:35 scaleFactor: 1 below the threshold, then log2 growth so a
    100k-node cluster syncs ~10x less often per node."""
    if nodes <= SCALE_THRESHOLD:
        return 1
    return int(math.ceil(math.log2(nodes) - math.log2(SCALE_THRESHOLD))) + 1


class StateSyncer:
    def __init__(self, local_state, catalog,
                 interval: float = DEFAULT_SYNC_INTERVAL,
                 cluster_size: Callable[[], int] = lambda: 1,
                 retry_fail_interval: float = RETRY_FAIL_INTERVAL,
                 jitter: float = 0.1):
        self.local = local_state
        self.catalog = catalog
        self.interval = interval
        self.cluster_size = cluster_size
        self.retry_fail_interval = retry_fail_interval
        self.jitter = jitter
        self._cond = threading.Condition()
        self._triggered = False
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.syncs_full = 0
        self.syncs_partial = 0
        self.failures = 0
        # last successful sync (wall clock): feeds the consul.ae.lag
        # gauge — seconds the local state has gone without a confirmed
        # catalog sync, the anti-entropy half of the visibility SLI
        # (a watcher can only see what AE pushed)
        self.last_success = time.time()

    # ---------------------------------------------------------------- pacing

    def full_interval(self) -> float:
        """Interval scaled by cluster size with ±jitter stagger
        (ae.go:155 Run → staggerFn)."""
        base = self.interval * scale_factor(self.cluster_size())
        return base * (1.0 + random.uniform(-self.jitter, self.jitter))

    # --------------------------------------------------------------- trigger

    def trigger(self) -> None:
        """Edge-trigger a partial sync (ae/trigger.go SyncChanges.Trigger)."""
        with self._cond:
            self._triggered = True
            self._cond.notify_all()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread:
            self._thread.join(timeout=5.0)

    def sync_full_now(self) -> int:
        """One blocking full pass (Agent.StartSync's initial sync)."""
        t0 = time.perf_counter()
        n = self.local.sync_full(self.catalog)
        self.syncs_full += 1
        # consul.ae.sync{type=full}: the anti-entropy pass the reference
        # times in agent/ae (StateSyncer full vs triggered partial)
        telemetry.measure_since(("ae", "sync"), t0,
                                labels={"type": "full"})
        self._mark_synced()
        return n

    def _mark_synced(self) -> None:
        self.last_success = time.time()
        telemetry.set_gauge(("ae", "lag"), 0.0)

    def lag(self) -> float:
        """Seconds since the catalog last confirmed a sync."""
        return max(0.0, time.time() - self.last_success)

    # ------------------------------------------------------------------ loop

    def _run(self) -> None:
        import time
        next_full = time.time() + self.full_interval()
        while True:
            with self._cond:
                if not self._triggered and self._running:
                    self._cond.wait(
                        timeout=max(0.0, next_full - time.time()))
                if not self._running:
                    return
                triggered = self._triggered
                self._triggered = False
            now = time.time()
            try:
                if now >= next_full:
                    # full sync supersedes any pending partial
                    self.sync_full_now()
                    next_full = now + self.full_interval()
                elif triggered:
                    t0 = time.perf_counter()
                    self.local.update_sync_state(self.catalog)
                    self.local.sync_changes(self.catalog)
                    self.syncs_partial += 1
                    telemetry.measure_since(("ae", "sync"), t0,
                                            labels={"type": "partial"})
                    self._mark_synced()
            except Exception:
                self.failures += 1
                telemetry.incr_counter(("ae", "sync_failed"))
                # the lag gauge grows only while syncs FAIL (success
                # resets it to 0): a flat-lining catalog shows up as a
                # climbing consul.ae.lag, the AE leg of the
                # commit-to-visibility SLI
                telemetry.set_gauge(("ae", "lag"), self.lag())
                next_full = min(next_full, now + self.retry_fail_interval)
