"""Trace spans: request-scoped IDs propagated across the RPC boundary.

The reference leans on go-metrics + hclog for causality; what operators
actually need from `consul debug` is "where did THIS write spend its
time" — so this module mints a trace ID at the HTTP/RPC entry point,
carries it through leader forwarding and blocking-query retries, and
records completed spans into a process-wide ring buffer that rides the
debug archive (debug.py capture) next to the thread dumps.

Design constraints, deliberate:

  * **Zero-dependency, bounded memory.**  A deque ring (SPAN_RING
    entries) guarded by one lock; a span record is a small dict.
  * **Explicit propagation across threads/sockets.**  A contextvar
    carries the current trace ID within a request thread; crossing the
    forward coalescer or a socket RPC attaches the ID to the envelope
    (never to the replicated raft command — payloads must stay
    byte-identical across replicas).
  * **Always-on but cheap.**  One perf_counter pair + one deque append
    per span; no sampling machinery until profiles say otherwise.
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import List, Optional

SPAN_RING = 2048

_ring: deque = deque(maxlen=SPAN_RING)
_lock = threading.Lock()
_seq = 0      # monotone span cursor (rides /v1/agent/traces?since=)
_current: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "consul_tpu_trace_id", default=None)


def new_trace_id() -> str:
    """128-bit random, hex — the X-Consul-Trace-Id wire form."""
    return uuid.uuid4().hex


_ID_MAX = 64
_ID_CHARS = frozenset("0123456789abcdefABCDEF-")


def sanitize_id(raw: Optional[str]) -> Optional[str]:
    """Validate a client-supplied trace id: hex/hyphen, <= 64 chars
    (new_trace_id's form, or a dashed UUID).  Anything else returns
    None so the caller mints a fresh id — an unbounded header must not
    occupy ring slots, RPC envelopes, and debug archives cluster-wide
    (the rpc method-label allowlist applies the same rule)."""
    if not raw or len(raw) > _ID_MAX:
        return None
    return raw if all(c in _ID_CHARS for c in raw) else None


def current_trace() -> Optional[str]:
    return _current.get()


def set_current(trace_id: Optional[str]):
    """Bind the thread/task-local current trace; returns the reset
    token (pass to `reset`)."""
    return _current.set(trace_id)


def reset(token) -> None:
    _current.reset(token)


def record(name: str, trace_id: Optional[str], start_wall: float,
           dur_s: float, **attrs) -> None:
    """Append one completed span.  `attrs` values must be JSON-safe
    scalars (they ride /v1/agent/traces and the debug archive).  Each
    span gets a monotone `seq` so pollers (the WAN probe, the
    federation view) can cursor with ?since= instead of re-downloading
    the whole ring."""
    global _seq
    rec = {
        "trace_id": trace_id or "",
        "name": name,
        "start": round(start_wall, 6),
        "dur_ms": round(dur_s * 1000.0, 3),
        "thread": threading.current_thread().name,
    }
    if attrs:
        rec["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    with _lock:
        _seq += 1
        rec["seq"] = _seq
        _ring.append(rec)


@contextmanager
def span(name: str, trace_id: Optional[str] = None, **attrs):
    """Record a span around the body.  trace_id defaults to the
    contextvar-bound current trace (empty string if none — spans
    without a trace still land in the ring for profiling)."""
    tid = trace_id if trace_id is not None else _current.get()
    wall = time.time()
    t0 = time.perf_counter()
    try:
        yield tid
    finally:
        record(name, tid, wall, time.perf_counter() - t0, **attrs)


def dump(limit: Optional[int] = None,
         trace_id: Optional[str] = None,
         since: int = 0) -> List[dict]:
    """Snapshot of the ring, oldest first; optionally filtered to one
    trace, to spans with seq > `since` (forward-paging cursor), and/or
    capped to the newest `limit` records."""
    with _lock:
        out = list(_ring)
    if since:
        out = [r for r in out if r.get("seq", 0) > since]
    if trace_id:
        out = [r for r in out if r["trace_id"] == trace_id]
    if limit is not None and limit >= 0:
        # out[-0:] is the WHOLE list — limit=0 must mean zero records
        out = out[-limit:] if limit else []
    return out


def last_seq() -> int:
    """The cursor horizon: every span ≤ this seq has been recorded
    (the ?since= echo when a filtered page comes back empty)."""
    with _lock:
        return _seq


def clear() -> None:
    with _lock:
        _ring.clear()
