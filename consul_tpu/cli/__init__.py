"""CLI — operator tooling over the HTTP client, registry-pattern dispatch
(reference: command/registry.go:18-45; each subcommand wraps the api/
client the same way the reference's command families do)."""
