import sys

from consul_tpu.cli.main import main

sys.exit(main())
