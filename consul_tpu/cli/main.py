"""`python -m consul_tpu.cli <command>` — the `consul` binary equivalent.

Commands mirror the reference's CLI families (command/ directory, 34
families — SURVEY.md §2.3): agent, members, kv, event, info, rtt, catalog,
services, session, snapshot, lock, watch, force-leave, leave, keygen,
version.  Each wraps the HTTP client (api/client.py), like the reference's
commands wrap the Go api client.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import secrets
import sys
import time

from consul_tpu.api.client import ApiError, Client
from consul_tpu.version import VERSION


def _addr_token(args):
    addr = args.http_addr or os.environ.get("CONSUL_HTTP_ADDR",
                                            "http://127.0.0.1:8500")
    if not addr.startswith("http"):
        addr = "http://" + addr
    token = getattr(args, "token", None) or \
        os.environ.get("CONSUL_HTTP_TOKEN")
    return addr, token


def _client(args) -> Client:
    addr, token = _addr_token(args)
    return Client(addr, token=token)


def cmd_version(args) -> int:
    print(f"consul-tpu v{VERSION}")
    return 0


def cmd_keygen(args) -> int:
    print(base64.b64encode(secrets.token_bytes(32)).decode())
    return 0


def cmd_members(args) -> int:
    status_names = {1: "alive", 2: "leaving", 3: "left", 4: "failed"}
    rows = _client(args).agent_members(
        segment=getattr(args, "segment", None) or None)
    print(f"{'Node':<20}{'Address':<22}{'Status':<10}Tags")
    for m in rows:
        if args.status and status_names.get(m["Status"]) != args.status:
            continue
        tags = ",".join(f"{k}={v}" for k, v in sorted(m["Tags"].items()))
        print(f"{m['Name']:<20}{m['Addr'] + ':' + str(m['Port']):<22}"
              f"{status_names.get(m['Status'], '?'):<10}{tags}")
    return 0


def cmd_info(args) -> int:
    me = _client(args).agent_self()
    print(json.dumps(me, indent=2))
    return 0


def cmd_kv(args) -> int:
    c = _client(args)
    if args.kv_cmd == "get":
        if args.recurse:
            for row in c.kv_list(args.key):
                print(f"{row['Key']}:{row['Value'].decode(errors='replace')}")
            return 0
        if args.keys:
            for k in c.kv_keys(args.key, separator=args.separator or ""):
                print(k)
            return 0
        row, _ = c.kv_get(args.key)
        if row is None:
            print(f"Error! No key exists at: {args.key}", file=sys.stderr)
            return 1
        if args.detailed:
            print(json.dumps({k: (v.decode(errors="replace")
                                  if isinstance(v, bytes) else v)
                              for k, v in row.items()}, indent=2))
        else:
            sys.stdout.write(row["Value"].decode(errors="replace") + "\n")
        return 0
    if args.kv_cmd == "put":
        value = args.value
        if value == "-":
            value = sys.stdin.read()
        elif value is not None and value.startswith("@"):
            value = open(value[1:], "rb").read()
        ok = c.kv_put(args.key, value if value is not None else b"",
                      flags=args.flags,
                      cas=args.cas, acquire=args.acquire,
                      release=args.release)
        if not ok:
            print("Error! Did not write to key", file=sys.stderr)
            return 1
        print(f"Success! Data written to: {args.key}")
        return 0
    if args.kv_cmd == "delete":
        ok = c.kv_delete(args.key, recurse=args.recurse)
        print(f"Success! Deleted key{'s under' if args.recurse else ''}: "
              f"{args.key}")
        return 0 if ok else 1
    if args.kv_cmd == "export":
        out = [{"key": r["Key"], "flags": r["Flags"],
                "value": base64.b64encode(r["Value"]).decode()}
               for r in c.kv_list(args.key or "")]
        print(json.dumps(out, indent=2))
        return 0
    if args.kv_cmd == "import":
        data = json.loads(sys.stdin.read())
        for row in data:
            c.kv_put(row["key"], base64.b64decode(row["value"]),
                     flags=row.get("flags", 0))
        print(f"Imported: {len(data)} keys")
        return 0
    return 2


def cmd_event(args) -> int:
    c = _client(args)
    if args.list:
        for e in c.event_list(args.name if args.name else None):
            print(f"{e['ID']:>4}  {e['Name']:<20} ltime={e['LTime']} "
                  f"coverage={e.get('Coverage', 0):.3f}")
        return 0
    out = c.event_fire(args.name, args.payload or "")
    print(f"Event ID: {out['ID']}")
    return 0


def cmd_rtt(args) -> int:
    c = _client(args)
    a = c.coordinate_node(args.node1)
    b = c.coordinate_node(args.node2 or "node0")
    if not a or not b:
        print("Error! Coordinates not available", file=sys.stderr)
        return 1

    # ComputeDistance (lib/rtt.go:13): euclidean + heights + adjustments
    import math
    ca, cb = a[0]["Coord"], b[0]["Coord"]
    d = math.sqrt(sum((x - y) ** 2 for x, y in zip(ca["Vec"], cb["Vec"])))
    rtt = d + ca["Height"] + cb["Height"] + ca["Adjustment"] + cb["Adjustment"]
    print(f"Estimated {args.node1} <-> {args.node2 or 'node0'} rtt: "
          f"{max(rtt, 0) * 1000:.3f} ms")
    return 0


def cmd_catalog(args) -> int:
    c = _client(args)
    if args.catalog_cmd == "nodes":
        for n in c.catalog_nodes(near=args.near):
            print(f"{n['Node']:<20}{n['Address']}")
        return 0
    if args.catalog_cmd == "services":
        for name, tags in c.catalog_services().items():
            print(f"{name:<24}{','.join(tags)}")
        return 0
    if args.catalog_cmd == "service":
        for r in c.catalog_service(args.name, near=args.near):
            print(f"{r['Node']:<20}{r['ServiceID']:<16}:{r['ServicePort']}")
        return 0
    return 2


def cmd_services(args) -> int:
    c = _client(args)
    if args.services_cmd == "register":
        c.agent_service_register(args.name, service_id=args.id,
                                 port=args.port,
                                 tags=args.tag or [])
        print(f"Registered service: {args.name}")
        return 0
    if args.services_cmd == "deregister":
        c.agent_service_deregister(args.id or args.name)
        print(f"Deregistered service: {args.id or args.name}")
        return 0
    return 2


def cmd_session(args) -> int:
    c = _client(args)
    for s in c.session_list():
        print(f"{s['ID']}  node={s['Node']} behavior={s['Behavior']} "
              f"ttl={s['TTL']}")
    return 0


def cmd_snapshot(args) -> int:
    from consul_tpu import snapshot as snapmod
    c = _client(args)
    if args.snapshot_cmd == "save":
        data = c.snapshot_save()
        # verify the archive before declaring success (the reference
        # re-reads + checksums on save, command/snapshot/save)
        try:
            state, meta = snapmod.read_archive(data)
        except snapmod.SnapshotError as e:
            print(f"Error verifying snapshot: {e}", file=sys.stderr)
            return 1
        with open(args.file, "wb") as f:
            f.write(data)
        print(f"Saved and verified snapshot to index {meta['Index']}")
        return 0
    if args.snapshot_cmd == "restore":
        with open(args.file, "rb") as f:
            c.snapshot_restore(f.read())
        print("Restored snapshot")
        return 0
    if args.snapshot_cmd == "inspect":
        try:
            info = snapmod.inspect(open(args.file, "rb").read())
        except snapmod.SnapshotError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        print(f"Created:  {info['Meta'].get('CreatedAt', '')}")
        print(f"Index:    {info['Meta']['Index']}")
        print(f"Version:  {info['Meta']['Version']}")
        print(f"Size:     {info['SizeBytes']}")
        for table, count in sorted(info["Tables"].items()):
            print(f"  {table}: {count}")
        return 0
    return 2


def cmd_lock(args) -> int:
    """consul lock (command/lock): hold a KV lock while running a child."""
    import subprocess
    c = _client(args)
    sid = c.lock_acquire(args.prefix + "/.lock", b"cli-lock")
    if sid is None:
        print("Error! Could not acquire lock", file=sys.stderr)
        return 1
    try:
        return subprocess.call(args.child)
    finally:
        c.lock_release(args.prefix + "/.lock", sid)


def cmd_watch(args) -> int:
    """consul watch over every plan type (command/watch,
    api/watch/watch.go:21,132)."""
    from consul_tpu.api.watch import WatchPlan
    c = _client(args)
    params = {k: v for k, v in {
        "key": args.key, "prefix": args.prefix,
        "service": args.service, "tag": args.tag,
        "state": args.state, "name": args.name,
        "passing": args.passing}.items() if v}
    try:
        plan = WatchPlan(c, args.type, wait=args.wait, **params)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2

    def handler(index, result):
        print(json.dumps({"Index": index, "Result": result}))
        sys.stdout.flush()

    plan.run(handler,
             max_events=1 if args.once else (args.max_events or None))
    return 0


def cmd_force_leave(args) -> int:
    _client(args).agent_force_leave(args.node)
    print(f"Force-left node: {args.node}")
    return 0


def cmd_leave(args) -> int:
    _client(args)._call("PUT", "/v1/agent/leave")
    print("Graceful leave complete")
    return 0


def cmd_config(args) -> int:
    """consul config (command/config): centralized config entries."""
    c = _client(args)
    if args.config_cmd == "write":
        if args.file == "-":
            entry = json.loads(sys.stdin.read())
        else:
            with open(args.file) as f:
                entry = json.loads(f.read())
        c.config_write(entry)
        print(f"Config entry written: "
              f"{entry.get('Kind')}/{entry.get('Name')}")
        return 0
    if args.config_cmd == "read":
        print(json.dumps(c.config_read(args.kind, args.name), indent=2))
        return 0
    if args.config_cmd == "list":
        for e in c.config_list(args.kind):
            print(e.get("Name", ""))
        return 0
    if args.config_cmd == "delete":
        c.config_delete(args.kind, args.name)
        print(f"Config entry deleted: {args.kind}/{args.name}")
        return 0
    return 1


def cmd_intention(args) -> int:
    """consul intention (command/intention)."""
    c = _client(args)
    if args.intention_cmd == "create":
        action = "deny" if args.deny else "allow"
        iid = c.intention_create(args.source, args.destination, action)
        print(f"Created: {args.source} => {args.destination} "
              f"({action}) id={iid}")
        return 0
    if args.intention_cmd == "list":
        for it in c.intention_list():
            print(f"{it['ID']}  {it['SourceName']} => "
                  f"{it['DestinationName']}  {it['Action']}")
        return 0
    if args.intention_cmd == "check":
        allowed = c.intention_check(args.source, args.destination)
        print("Allowed" if allowed else "Denied")
        return 0 if allowed else 2
    if args.intention_cmd == "delete":
        c.intention_delete(args.id)
        print(f"Deleted: {args.id}")
        return 0
    if args.intention_cmd == "match":
        out = c.intention_match(args.by, args.name)
        for rows in out.values():
            for it in rows:
                print(f"{it['SourceName']} => "
                      f"{it['DestinationName']}  {it['Action']}")
        return 0
    return 1


def cmd_connect(args) -> int:
    """consul connect ca|proxy (command/connect/ca, command/connect/proxy)."""
    c = _client(args)
    if args.connect_cmd == "envoy":
        # `consul connect envoy -bootstrap` (command/connect/envoy):
        # emit the envoy v3 bootstrap that attaches a STOCK envoy to
        # this agent's gRPC ADS — node.id carries the sidecar service
        # id, the xds cluster dials the agent's GRPC port over HTTP/2.
        if not args.bootstrap:
            print("only -bootstrap mode is supported (no envoy binary "
                  "is shipped); pass -bootstrap", file=sys.stderr)
            return 1
        if bool(args.sidecar_for) == bool(args.proxy_id):
            print("exactly one of -proxy-id or -sidecar-for is "
                  "required", file=sys.stderr)
            return 1
        me = c.agent_self()
        grpc_port = (me.get("xDS") or {}).get("Port", -1)
        if grpc_port is None or grpc_port < 0:
            print("agent has no gRPC xDS listener (set ports.grpc)",
                  file=sys.stderr)
            return 1
        if args.sidecar_for:
            # resolve the SERVICE name to its registered sidecar
            # proxy (the reference scans local services for a
            # connect-proxy whose destination matches)
            rows = c.health_connect(args.sidecar_for)
            if not rows:
                print(f"no sidecar proxy registered for service "
                      f"{args.sidecar_for!r}", file=sys.stderr)
                return 1
            proxy_id = rows[0]["Service"]["ID"]
            cluster = args.sidecar_for
        else:
            proxy_id = args.proxy_id
            cluster = proxy_id
        bootstrap = {
            "node": {"id": proxy_id, "cluster": cluster,
                     "metadata": {"namespace": "default",
                                  "envoy_version": "1.20.0"}},
            "static_resources": {"clusters": [{
                "name": "consul_xds",
                "type": "STATIC",
                "connect_timeout": "1s",
                "typed_extension_protocol_options": {
                    "envoy.extensions.upstreams.http.v3."
                    "HttpProtocolOptions": {
                        "@type": "type.googleapis.com/envoy.extensions"
                                 ".upstreams.http.v3."
                                 "HttpProtocolOptions",
                        "explicit_http_config": {
                            "http2_protocol_options": {}}}},
                "load_assignment": {
                    "cluster_name": "consul_xds",
                    "endpoints": [{"lb_endpoints": [{"endpoint": {
                        "address": {"socket_address": {
                            "address": "127.0.0.1",
                            "port_value": grpc_port}}}}]}]},
            }]},
            "dynamic_resources": {
                "lds_config": {"ads": {},
                               "resource_api_version": "V3"},
                "cds_config": {"ads": {},
                               "resource_api_version": "V3"},
                "ads_config": {
                    "api_type": "GRPC",
                    "transport_api_version": "V3",
                    "grpc_services": [{"envoy_grpc": {
                        "cluster_name": "consul_xds"}}]}},
            "admin": {"address": {"socket_address": {
                "address": "127.0.0.1",
                "port_value": args.admin_bind}}},
        }
        print(json.dumps(bootstrap, indent=2))
        return 0
    if args.connect_cmd == "proxy":
        from consul_tpu.connect.proxy import ApiProxy
        ups = []
        for spec in args.upstream or []:
            name, _, port = spec.partition(":")
            ups.append((name, int(port or 0)))
        host, _, lp = (args.listen or "127.0.0.1:0").partition(":")
        proxy = ApiProxy(c, args.service,
                         listen=(host or "127.0.0.1", int(lp or 0)),
                         local_app_port=args.local_app_port,
                         upstreams=ups)
        proxy.start()
        print(f"proxy for {args.service}: public "
              f"127.0.0.1:{proxy.public.port}" + "".join(
                  f", upstream {n} -> 127.0.0.1:{u.port}"
                  for (n, _), u in zip(ups, proxy.upstreams)),
              flush=True)
        import time as _t
        try:
            while True:
                _t.sleep(1.0)
        except KeyboardInterrupt:
            proxy.stop()
        return 0
    if args.ca_cmd == "roots":
        out = c.connect_ca_roots()
        for r in out["Roots"]:
            mark = "*" if r["Active"] else " "
            print(f"{mark} {r['ID']}")
        return 0
    if args.ca_cmd == "rotate":
        out = c.connect_ca_rotate()
        print(f"Rotated: active root {out['ActiveRootID']}")
        return 0
    if args.ca_cmd == "get-config":
        print(json.dumps(c.connect_ca_config(), indent=2))
        return 0
    if args.ca_cmd == "set-config":
        # never close sys.stdin: main() is called in-process
        if args.config_file == "-":
            cfg = json.loads(sys.stdin.read())
        else:
            with open(args.config_file) as f:
                cfg = json.loads(f.read())
        c.connect_ca_set_config(cfg)
        print("Configuration updated")
        return 0
    return 1


def cmd_login(args) -> int:
    """consul login (command/login): bearer JWT → ACL token sink."""
    c = _client(args)
    if args.bearer_token_file == "-":
        bearer = sys.stdin.read().strip()   # don't close stdin
    else:
        with open(args.bearer_token_file) as f:
            bearer = f.read().strip()
    out = c.acl_login(args.method, bearer)
    secret = out.get("SecretID", "")
    if args.token_sink_file:
        import os
        # 0600: the sink holds a live credential (the reference writes
        # token sinks with restrictive perms)
        fd = os.open(args.token_sink_file,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(secret)
        print(f"Token written to {args.token_sink_file}")
    else:
        print(secret)
    return 0


def cmd_logout(args) -> int:
    """consul logout: destroy the login token in use."""
    _client(args).acl_logout()
    print("Logged out")
    return 0


def cmd_tls(args) -> int:
    """consul tls ca|cert create (command/tls): local PKI material."""
    from consul_tpu.tlsutil import Configurator
    import os
    if args.tls_cmd == "ca":
        # refuse to clobber: every issued cert chains to THIS keypair
        # (the reference errors with "file ... already exists")
        for path in ("consul-agent-ca.pem", "consul-agent-ca-key.pem"):
            if os.path.exists(path):
                print(f"file {path} already exists", file=sys.stderr)
                return 1
        tls = Configurator(dc=args.dc)
        with open("consul-agent-ca.pem", "w") as f:
            f.write(tls.ca_pem)
        with open("consul-agent-ca-key.pem", "w") as f:
            f.write(tls.ca_key_pem)
        print("==> Saved consul-agent-ca.pem")
        print("==> Saved consul-agent-ca-key.pem")
        return 0
    if args.tls_cmd == "cert":
        if not (os.path.exists("consul-agent-ca.pem")
                and os.path.exists("consul-agent-ca-key.pem")):
            print("CA files not found: run `tls ca create` first",
                  file=sys.stderr)
            return 1
        with open("consul-agent-ca.pem") as f:
            ca_pem = f.read()
        with open("consul-agent-ca-key.pem") as f:
            ca_key = f.read()
        tls = Configurator(dc=args.dc, ca_cert_pem=ca_pem,
                           ca_key_pem=ca_key)
        name = args.name or ("server" if args.server else "client")
        cert, key = tls.sign_cert(name, server=args.server)
        role = "server" if args.server else "client"
        # increment like the reference: never clobber an issued pair
        i = 0
        while os.path.exists(f"{args.dc}-{role}-consul-{i}.pem"):
            i += 1
        base = f"{args.dc}-{role}-consul-{i}"
        with open(f"{base}.pem", "w") as f:
            f.write(cert)
        with open(f"{base}-key.pem", "w") as f:
            f.write(key)
        print(f"==> Saved {base}.pem")
        print(f"==> Saved {base}-key.pem")
        return 0
    return 1


def cmd_maint(args) -> int:
    """consul maint (command/maint): toggle node or service
    maintenance mode via the reserved critical checks."""
    c = _client(args)
    if args.enable and args.disable:
        print("Only one of -enable or -disable may be provided",
              file=sys.stderr)
        return 1
    if not args.enable and not args.disable:
        # no flags: show current maintenance state
        checks = c._call("GET", "/v1/agent/checks")[0]
        rows = [chk for cid, chk in checks.items()
                if cid == "_node_maintenance"
                or cid.startswith("_service_maintenance:")]
        if not rows:
            print("Node and all services are in normal mode")
            return 0
        for chk in rows:
            scope = "node" if chk["CheckID"] == "_node_maintenance" \
                else f"service {chk['ServiceID']}"
            print(f"{scope}: maintenance enabled "
                  f"(reason: {chk.get('Output', '')})")
        return 0
    enable = bool(args.enable)
    if args.service:
        c.agent_service_maintenance(args.service, enable,
                                    reason=args.reason or "")
        print(f"Service maintenance {'enabled' if enable else 'disabled'}"
              f" for {args.service}")
    else:
        c.agent_maintenance(enable, reason=args.reason or "")
        print(f"Node maintenance "
              f"{'enabled' if enable else 'disabled'}")
    return 0


def cmd_join(args) -> int:
    c = _client(args)
    ok = 0
    for addr in args.address:
        try:
            c.agent_join(addr)
            ok += 1
        except Exception as e:
            print(f"Error joining {addr}: {e}", file=sys.stderr)
    print(f"Successfully joined cluster by contacting {ok} nodes.")
    return 0 if ok else 1


def cmd_exec(args) -> int:
    """consul exec (command/exec): run a command cluster-wide via KV +
    events; waits a quiet period after the last response so slower
    nodes aren't dropped, then cleans the session's KV prefix."""
    c = _client(args)
    body = json.dumps({"Command": args.command,
                       "Wait": args.wait}).encode()
    out = c._call("PUT", "/v1/exec", None, body)[0]
    session = out["Session"]
    deadline = time.time() + args.wait + 5
    quiet_s = 1.0
    done = {}
    last_new = time.time()
    try:
        while time.time() < deadline:
            res = c._call("GET", f"/v1/exec/{session}")[0]
            for node, rec in res.items():
                if rec.get("ExitCode") is not None and node not in done:
                    done[node] = rec
                    last_new = time.time()
                    print(f"{node}: exit={rec['ExitCode']}")
                    if rec.get("Output"):
                        print("    " + base64.b64decode(
                            rec["Output"]).decode(
                            errors="replace").strip())
            if done and time.time() - last_new > quiet_s:
                break
            time.sleep(0.3)
    finally:
        # initiator removes the session prefix (the reference cleans
        # _rexec after the wait window) — exec must not grow KV forever
        try:
            c._call("DELETE", f"/v1/kv/_rexec/{session}/",
                    {"recurse": ""})
        except Exception:
            pass
    if not done:
        print("no responses (is enable_remote_exec set?)",
              file=sys.stderr)
        return 1
    return 0


def cmd_keyring(args) -> int:
    """consul keyring (command/keyring): gossip key lifecycle."""
    c = _client(args)
    if args.list_keys:
        rings = c._call("GET", "/v1/operator/keyring")[0]
        for ring in rings:
            print(f"{ring['Datacenter']} (LAN):")
            for k, n in ring["Keys"].items():
                print(f"  {k} [{n}/{ring['NumNodes']}]")
        return 0
    body = None
    verb = None
    if args.install:
        verb, body = "POST", {"Key": args.install}
    elif args.use:
        verb, body = "PUT", {"Key": args.use}
    elif args.remove:
        verb, body = "DELETE", {"Key": args.remove}
    else:
        print("one of -list, -install, -use, -remove required",
              file=sys.stderr)
        return 2
    c._call(verb, "/v1/operator/keyring", None,
            json.dumps(body).encode())
    print("Keyring operation completed")
    return 0


def cmd_monitor(args) -> int:
    """consul monitor (command/monitor): stream agent logs."""
    import urllib.request
    addr, token = _addr_token(args)
    url = (f"{addr}/v1/agent/monitor"
           f"?loglevel={args.log_level}&wait={args.wait}")
    req = urllib.request.Request(url)
    if token:
        req.add_header("X-Consul-Token", token)
    with urllib.request.urlopen(req, timeout=args.wait + 30) as resp:
        while True:
            chunk = resp.read(4096)
            if not chunk:
                break
            sys.stdout.write(chunk.decode(errors="replace"))
            sys.stdout.flush()
    return 0


def cmd_debug(args) -> int:
    """consul debug (command/debug): capture a diagnostic archive FROM
    THE AGENT over its HTTP API (metrics/self/members per interval +
    host info from this process; the reference pulls from the agent's
    debug endpoints too)."""
    import io as _io
    import tarfile
    from consul_tpu.debug import host_info, thread_dump

    c = _client(args)
    buf = _io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        def add(name, data):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, _io.BytesIO(data))

        add("host.json", json.dumps(host_info(), indent=2).encode())
        add("cli_threads.txt", thread_dump().encode())
        try:
            add("agent.json", json.dumps(
                c._call("GET", "/v1/agent/self")[0], indent=2).encode())
            add("members.json", json.dumps(
                c._call("GET", "/v1/agent/members",
                        {"limit": 1000})[0], indent=2).encode())
            for i in range(args.intervals):
                add(f"{i}/metrics.json", json.dumps(
                    c._call("GET", "/v1/agent/metrics")[0],
                    indent=2).encode())
                # prometheus exposition snapshot (the reference debug
                # archive captures the scrape format too)
                _, _, prom_raw = c._call("GET", "/v1/agent/metrics",
                                         {"format": "prometheus"})
                add(f"{i}/metrics.prom", prom_raw or b"")
                if i < args.intervals - 1:
                    time.sleep(args.interval)
            # the agent's trace-span ring buffer (one trace id follows
            # a forwarded write follower → leader → apply)
            add("trace.json", json.dumps(
                c._call("GET", "/v1/agent/traces")[0],
                indent=2).encode())
        except Exception as e:
            add("capture_error.txt",
                f"agent capture failed: {e}".encode())
    blob = buf.getvalue()
    with open(args.output, "wb") as f:
        f.write(blob)
    print(f"Saved debug archive: {args.output} ({len(blob)} bytes)")
    return 0


def cmd_operator(args) -> int:
    """consul operator raft list-peers / autopilot state
    (command/operator)."""
    c = _client(args)
    if args.operator_cmd == "raft":
        cfg = c._call("GET", "/v1/operator/raft/configuration")[0]
        print(f"{'Node':<12} {'ID':<12} {'Leader':<7} Voter")
        for s in cfg["Servers"]:
            print(f"{s['Node']:<12} {s['ID']:<12} "
                  f"{str(s['Leader']).lower():<7} "
                  f"{str(s['Voter']).lower()}")
        return 0
    if args.operator_cmd == "autopilot":
        h = c._call("GET", "/v1/operator/autopilot/health")[0]
        print(f"Healthy: {h['Healthy']}")
        print(f"FailureTolerance: {h['FailureTolerance']}")
        for s in h["Servers"]:
            print(f"  {s['ID']}: healthy={s['Healthy']} "
                  f"leader={s['Leader']} last_contact={s['LastContact']}")
        return 0
    return 2


def cmd_reload(args) -> int:
    """consul reload (command/reload): trigger a config reload."""
    out = _client(args)._call("PUT", "/v1/agent/reload")[0]
    print("Configuration reload triggered")
    if out.get("reloaded"):
        print("  reloaded: " + ", ".join(out["reloaded"]))
    if out.get("restart_required"):
        print("  restart required for: "
              + ", ".join(out["restart_required"]))
    return 0


def cmd_validate(args) -> int:
    """consul validate (command/validate): check config files parse."""
    from consul_tpu import runtime_config as rcfg
    try:
        rcfg.load(files=[args.file])
    except rcfg.ConfigError as e:
        print(f"Config validation failed: {e}", file=sys.stderr)
        return 1
    print("Configuration is valid!")
    return 0


def cmd_agent(args) -> int:
    """Run an agent (command/agent) — oracle + store + HTTP API."""
    from consul_tpu.agent import Agent
    from consul_tpu.config import GossipConfig, SimConfig

    if args.config_file or args.config_dir:
        # config pipeline: files/dirs ← CLI flags (builder.go precedence);
        # sim flags ride the same merge so nothing is silently dropped
        sim_flags = {k: v for k, v in {
            "n_nodes": args.sim_nodes, "rumor_slots": args.rumor_slots,
            "p_loss": args.p_loss, "seed": args.seed}.items()
            if v is not None}
        a = Agent.from_config(
            config_files=args.config_file or (),
            config_dirs=args.config_dir or (),
            node_name=args.node, datacenter=args.datacenter,
            http_port=args.http_port,
            sim=sim_flags or None,
            wan_defaults=args.wan_defaults)
    else:
        gossip = GossipConfig.wan() if args.wan_defaults \
            else GossipConfig.lan()
        sim = SimConfig(n_nodes=args.sim_nodes or 64,
                        rumor_slots=args.rumor_slots or 16,
                        p_loss=args.p_loss if args.p_loss is not None
                        else 0.01,
                        seed=args.seed or 0)
        a = Agent(gossip, sim, node_name=args.node or "node0",
                  http_port=args.http_port
                  if args.http_port is not None else 8500,
                  dc=args.datacenter or "dc1")
    a.start(tick_seconds=args.tick_seconds)
    print(f"==> consul-tpu agent running")
    print(f"       Node name: {a.node_name}")
    print(f"      Datacenter: {a.api.dc}")
    print(f"       HTTP addr: {a.http_address}")
    print(f"       Sim nodes: {a.oracle.n_nodes}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("==> Caught signal: interrupt — gracefully shutting down")
        a.stop()
    return 0


def cmd_acl(args) -> int:
    """`consul acl ...` family (command/acl/)."""
    c = _client(args)
    sub, obj = args.acl_cmd, getattr(args, "acl_obj", None)
    if sub == "bootstrap":
        out = c.acl_bootstrap()
        print(f"AccessorID:   {out['AccessorID']}")
        print(f"SecretID:     {out['SecretID']}")
        return 0
    if sub == "policy":
        if obj == "create":
            rules = args.rules
            if rules.startswith("@"):
                with open(rules[1:]) as f:
                    rules = f.read()
            out = c.acl_policy_create(args.name, rules,
                                      args.description or "")
            print(f"ID:    {out['ID']}\nName:  {out['Name']}")
            return 0
        if obj == "list":
            for p in c.acl_policy_list():
                print(f"{p['Name']}:\n   ID: {p['ID']}\n   "
                      f"Description: {p['Description']}")
            return 0
        if obj == "read":
            p = c.acl_policy_read(args.id)
            print(f"ID:    {p['ID']}\nName:  {p['Name']}\nRules:")
            print(p["Rules"])
            return 0
        if obj == "delete":
            c.acl_policy_delete(args.id)
            print(f"Policy {args.id} deleted")
            return 0
    if sub == "token":
        if obj == "create":
            # -service-identity web / web:dc1,dc2 and
            # -node-identity n1:dc1 (command/acl/token/create flags)
            sids = []
            for spec in args.service_identity or []:
                name, _, dcs = spec.partition(":")
                sids.append(dict(
                    {"ServiceName": name},
                    **({"Datacenters": dcs.split(",")} if dcs else {})))
            nids = []
            for spec in args.node_identity or []:
                name, _, dc = spec.partition(":")
                if not dc:
                    print("-node-identity requires NAME:DATACENTER",
                          file=sys.stderr)
                    return 1
                nids.append({"NodeName": name, "Datacenter": dc})
            out = c.acl_token_create(args.policy_name or [],
                                     args.description or "",
                                     service_identities=sids or None,
                                     node_identities=nids or None)
            print(f"AccessorID:   {out['AccessorID']}")
            print(f"SecretID:     {out['SecretID']}")
            for s in out.get("ServiceIdentities") or []:
                print(f"Service Identity: {s['ServiceName']}"
                      + (f" ({', '.join(s['Datacenters'])})"
                         if s.get("Datacenters") else ""))
            for n in out.get("NodeIdentities") or []:
                print(f"Node Identity: {n['NodeName']} "
                      f"({n['Datacenter']})")
            return 0
        if obj == "list":
            for t in c.acl_token_list():
                print(f"AccessorID:   {t['AccessorID']}")
                print(f"Description:  {t['Description']}")
                print(f"Policies:     "
                      f"{', '.join(p['Name'] for p in t['Policies'])}")
                for s in t.get("ServiceIdentities") or []:
                    print(f"Service Identity: {s['ServiceName']}")
                for n in t.get("NodeIdentities") or []:
                    print(f"Node Identity: {n['NodeName']} "
                          f"({n['Datacenter']})")
                print()
            return 0
        if obj == "read":
            t = c.acl_token_self() if args.id == "self" else \
                c.acl_token_read(args.id)
            print(json.dumps(t, indent=2))
            return 0
        if obj == "delete":
            c.acl_token_delete(args.id)
            print(f"Token {args.id} deleted")
            return 0
    print("usage: consul-tpu acl {bootstrap|policy|token} ...",
          file=sys.stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="consul-tpu")
    p.add_argument("-http-addr", "--http-addr", dest="http_addr", default=None)
    p.add_argument("-token", "--token", dest="token", default=None)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("acl")
    aclsub = sp.add_subparsers(dest="acl_cmd", required=True)
    aclsub.add_parser("bootstrap")
    pol = aclsub.add_parser("policy")
    polsub = pol.add_subparsers(dest="acl_obj", required=True)
    x = polsub.add_parser("create")
    x.add_argument("-name", required=True)
    x.add_argument("-rules", required=True)
    x.add_argument("-description", default="")
    polsub.add_parser("list")
    for name in ("read", "delete"):
        x = polsub.add_parser(name)
        x.add_argument("-id", required=True)
    tok = aclsub.add_parser("token")
    toksub = tok.add_subparsers(dest="acl_obj", required=True)
    x = toksub.add_parser("create")
    x.add_argument("-policy-name", action="append")
    x.add_argument("-description", default="")
    x.add_argument("-service-identity", action="append",
                   dest="service_identity")
    x.add_argument("-node-identity", action="append",
                   dest="node_identity")
    toksub.add_parser("list")
    for name in ("read", "delete"):
        x = toksub.add_parser(name)
        x.add_argument("-id", required=True)
    sp.set_defaults(fn=cmd_acl)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser("keygen").set_defaults(fn=cmd_keygen)
    sp = sub.add_parser("members")
    sp.add_argument("-status", default=None)
    sp.add_argument("-segment", default=None)
    sp.set_defaults(fn=cmd_members)
    sub.add_parser("info").set_defaults(fn=cmd_info)

    sp = sub.add_parser("kv")
    kvsub = sp.add_subparsers(dest="kv_cmd", required=True)
    g = kvsub.add_parser("get")
    g.add_argument("key")
    g.add_argument("-recurse", action="store_true")
    g.add_argument("-keys", action="store_true")
    g.add_argument("-separator", default="/")
    g.add_argument("-detailed", action="store_true")
    pu = kvsub.add_parser("put")
    pu.add_argument("key")
    pu.add_argument("value", nargs="?", default=None)
    pu.add_argument("-flags", type=int, default=0)
    pu.add_argument("-cas", type=int, default=None)
    pu.add_argument("-acquire", default=None)
    pu.add_argument("-release", default=None)
    d = kvsub.add_parser("delete")
    d.add_argument("key")
    d.add_argument("-recurse", action="store_true")
    e = kvsub.add_parser("export")
    e.add_argument("key", nargs="?", default="")
    kvsub.add_parser("import")
    sp.set_defaults(fn=cmd_kv)

    sp = sub.add_parser("event")
    sp.add_argument("-name", required=False)
    sp.add_argument("payload", nargs="?", default="")
    sp.add_argument("-list", action="store_true")
    sp.set_defaults(fn=cmd_event)

    sp = sub.add_parser("rtt")
    sp.add_argument("node1")
    sp.add_argument("node2", nargs="?", default=None)
    sp.set_defaults(fn=cmd_rtt)

    sp = sub.add_parser("catalog")
    csub = sp.add_subparsers(dest="catalog_cmd", required=True)
    n = csub.add_parser("nodes")
    n.add_argument("-near", default=None)
    csub.add_parser("services")
    svc = csub.add_parser("service")
    svc.add_argument("name")
    svc.add_argument("-near", default=None)
    sp.set_defaults(fn=cmd_catalog)

    sp = sub.add_parser("services")
    ssub = sp.add_subparsers(dest="services_cmd", required=True)
    r = ssub.add_parser("register")
    r.add_argument("-name", required=True)
    r.add_argument("-id", default=None)
    r.add_argument("-port", type=int, default=0)
    r.add_argument("-tag", action="append")
    dr = ssub.add_parser("deregister")
    dr.add_argument("-name", default=None)
    dr.add_argument("-id", default=None)
    sp.set_defaults(fn=cmd_services)

    sub.add_parser("session").set_defaults(fn=cmd_session)

    sp = sub.add_parser("snapshot")
    snsub = sp.add_subparsers(dest="snapshot_cmd", required=True)
    for name in ("save", "restore", "inspect"):
        x = snsub.add_parser(name)
        x.add_argument("file")
    sp.set_defaults(fn=cmd_snapshot)

    sp = sub.add_parser("lock")
    sp.add_argument("prefix")
    sp.add_argument("child", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_lock)

    sp = sub.add_parser("watch")
    sp.add_argument("-type", default="key",
                    choices=["key", "keyprefix", "services", "nodes",
                             "service", "checks", "event"])
    sp.add_argument("-key", default=None)
    sp.add_argument("-prefix", default=None)
    sp.add_argument("-service", default=None)
    sp.add_argument("-tag", default=None)
    sp.add_argument("-state", default=None)
    sp.add_argument("-name", default=None)
    sp.add_argument("-passing", action="store_true")
    sp.add_argument("-wait", default="60s")
    sp.add_argument("-once", action="store_true")
    sp.add_argument("--max-events", type=int, default=0)
    sp.set_defaults(fn=cmd_watch)

    sp = sub.add_parser("force-leave")
    sp.add_argument("node")
    sp.set_defaults(fn=cmd_force_leave)
    sub.add_parser("leave").set_defaults(fn=cmd_leave)

    sp = sub.add_parser("config")
    csub = sp.add_subparsers(dest="config_cmd", required=True)
    x = csub.add_parser("write")
    x.add_argument("file")
    x = csub.add_parser("read")
    x.add_argument("-kind", required=True)
    x.add_argument("-name", required=True)
    x = csub.add_parser("list")
    x.add_argument("-kind", required=True)
    x = csub.add_parser("delete")
    x.add_argument("-kind", required=True)
    x.add_argument("-name", required=True)
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("intention")
    isub = sp.add_subparsers(dest="intention_cmd", required=True)
    x = isub.add_parser("create")
    x.add_argument("source")
    x.add_argument("destination")
    x.add_argument("-deny", action="store_true")
    x = isub.add_parser("check")
    x.add_argument("source")
    x.add_argument("destination")
    x = isub.add_parser("delete")
    x.add_argument("id")
    x = isub.add_parser("match")
    x.add_argument("-by", default="destination",
                   choices=["source", "destination"])
    x.add_argument("name")
    isub.add_parser("list")
    sp.set_defaults(fn=cmd_intention)

    sp = sub.add_parser("connect")
    cosub = sp.add_subparsers(dest="connect_cmd", required=True)
    ca = cosub.add_parser("ca")
    casub = ca.add_subparsers(dest="ca_cmd", required=True)
    casub.add_parser("roots")
    casub.add_parser("rotate")
    casub.add_parser("get-config")
    x = casub.add_parser("set-config")
    x.add_argument("-config-file", dest="config_file", default="-")
    ev = cosub.add_parser("envoy")
    ev.add_argument("-sidecar-for", dest="sidecar_for", default="")
    ev.add_argument("-proxy-id", dest="proxy_id", default="")
    ev.add_argument("-admin-bind", dest="admin_bind", type=int,
                    default=19000)
    ev.add_argument("-bootstrap", action="store_true",
                    help="print the bootstrap and exit (the only mode "
                         "— no envoy binary is shipped)")
    px = cosub.add_parser("proxy")
    px.add_argument("-service", required=True)
    px.add_argument("-listen", default="127.0.0.1:0",
                    help="public mTLS listener host:port")
    px.add_argument("-local-app-port", dest="local_app_port",
                    type=int, default=0)
    px.add_argument("-upstream", action="append",
                    help="name:local_bind_port (repeatable)")
    sp.set_defaults(fn=cmd_connect)

    sp = sub.add_parser("login")
    sp.add_argument("-method", required=True)
    sp.add_argument("-bearer-token-file", dest="bearer_token_file",
                    required=True)
    sp.add_argument("-token-sink-file", dest="token_sink_file",
                    default="")
    sp.set_defaults(fn=cmd_login)

    sub.add_parser("logout").set_defaults(fn=cmd_logout)

    sp = sub.add_parser("tls")
    tsub = sp.add_subparsers(dest="tls_cmd", required=True)
    x = tsub.add_parser("ca")
    x.add_argument("tls_action", choices=["create"])
    x.add_argument("-dc", default="dc1")
    x = tsub.add_parser("cert")
    x.add_argument("tls_action", choices=["create"])
    x.add_argument("-dc", default="dc1")
    x.add_argument("-server", action="store_true")
    x.add_argument("-name", default="")
    sp.set_defaults(fn=cmd_tls)

    sp = sub.add_parser("maint")
    sp.add_argument("-enable", action="store_true")
    sp.add_argument("-disable", action="store_true")
    sp.add_argument("-reason", default="")
    sp.add_argument("-service", default="")
    sp.set_defaults(fn=cmd_maint)

    sp = sub.add_parser("join")
    sp.add_argument("address", nargs="+")
    sp.set_defaults(fn=cmd_join)

    sp = sub.add_parser("exec")
    sp.add_argument("command")
    sp.add_argument("-wait", type=float, default=10.0)
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("keyring")
    sp.add_argument("-list", dest="list_keys", action="store_true")
    sp.add_argument("-install", default=None)
    sp.add_argument("-use", default=None)
    sp.add_argument("-remove", default=None)
    sp.set_defaults(fn=cmd_keyring)

    sp = sub.add_parser("monitor")
    sp.add_argument("-log-level", default="INFO")
    sp.add_argument("-wait", type=int, default=30)
    sp.set_defaults(fn=cmd_monitor)

    sp = sub.add_parser("debug")
    sp.add_argument("-output", default="consul-debug.tar.gz")
    sp.add_argument("-intervals", type=int, default=2)
    sp.add_argument("-interval", type=float, default=0.5)
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("operator")
    osub = sp.add_subparsers(dest="operator_cmd", required=True)
    osub.add_parser("raft")
    osub.add_parser("autopilot")
    sp.set_defaults(fn=cmd_operator)

    sub.add_parser("reload").set_defaults(fn=cmd_reload)

    sp = sub.add_parser("validate")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_validate)

    sp = sub.add_parser("agent")
    # None = not given, so explicit flags are distinguishable from
    # defaults and win over config files (builder precedence)
    sp.add_argument("-node", default=None)
    sp.add_argument("-datacenter", "-dc", default=None)
    sp.add_argument("-http-port", type=int, default=None)
    sp.add_argument("-sim-nodes", type=int, default=None)
    sp.add_argument("-rumor-slots", type=int, default=None)
    sp.add_argument("-p-loss", type=float, default=None)
    sp.add_argument("-seed", type=int, default=None)
    sp.add_argument("-tick-seconds", type=float, default=0.05)
    sp.add_argument("-wan-defaults", action="store_true")
    sp.add_argument("-config-file", action="append", default=None)
    sp.add_argument("-config-dir", action="append", default=None)
    sp.set_defaults(fn=cmd_agent)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except ConnectionError as e:
        print(f"Error connecting to agent: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
