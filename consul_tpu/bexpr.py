"""Boolean filter expressions for `?filter=` query parameters.

The reference filters most list endpoints through hashicorp/go-bexpr
(wired in agent/http.go parseFilter callers, e.g. agent_endpoint.go
AgentServices/AgentChecks, catalog and health endpoints).  This module
implements the same expression grammar over the JSON-shaped dicts this
framework's API returns:

  selector  := Ident ('.' Ident | '["key"]')*
  compare   := selector ('=='|'!='|'contains'|'not contains'|
                         'matches'|'not matches') value
             | value ('in'|'not in') selector
             | selector 'is empty' | selector 'is not empty'
  logical   := 'and' / 'or' / 'not' / parentheses

Values are double/backtick-quoted strings, numbers, or bare words.
Comparisons coerce the literal to the field's type (int/float/bool)
before comparing, like bexpr's reflection-driven coercion.  A selector
that walks off the data (unknown key) evaluates as an empty value —
`is empty` is true, every match is false — so heterogeneous rows (node
meta maps and the like) filter cleanly instead of erroring the request.

Parse errors raise BexprError; HTTP callers turn that into 400 the way
the reference rejects malformed filters.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

__all__ = ["BexprError", "compile_filter", "Filter"]


class BexprError(ValueError):
    """Malformed filter expression (400 Bad Request at the API)."""


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lparen>\() |
      (?P<rparen>\)) |
      (?P<op>==|!=) |
      (?P<string>"(?:[^"\\]|\\.)*"|`[^`]*`) |
      (?P<number>-?\d+(?:\.\d+)?(?!\w)) |
      (?P<dot>\.) |
      (?P<lbracket>\[) |
      (?P<rbracket>\]) |
      (?P<word>[A-Za-z_][A-Za-z0-9_-]*)
    )""", re.VERBOSE)

# words that terminate a selector / act as operators
_KEYWORDS = {"and", "or", "not", "in", "contains", "matches", "is",
             "empty"}


class _Token:
    __slots__ = ("kind", "text")

    def __init__(self, kind: str, text: str):
        self.kind = kind
        self.text = text

    def __repr__(self):  # pragma: no cover
        return f"<{self.kind}:{self.text}>"


def _tokenize(src: str) -> List[_Token]:
    out: List[_Token] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            rest = src[pos:].strip()
            if not rest:
                break
            raise BexprError(f"invalid token at: {rest[:20]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind is None or not text.strip():
            continue
        out.append(_Token(kind, text.strip()))
    return out


def _unquote(text: str) -> str:
    if text.startswith("`"):
        return text[1:-1]
    body = text[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


class _EMPTY:
    """Sentinel: selector walked off the data."""


EMPTY = _EMPTY()


class _Node:
    def eval(self, data: Any) -> bool:  # pragma: no cover
        raise NotImplementedError


class _And(_Node):
    def __init__(self, parts):
        self.parts = parts

    def eval(self, data):
        return all(p.eval(data) for p in self.parts)


class _Or(_Node):
    def __init__(self, parts):
        self.parts = parts

    def eval(self, data):
        return any(p.eval(data) for p in self.parts)


class _Not(_Node):
    def __init__(self, inner):
        self.inner = inner

    def eval(self, data):
        return not self.inner.eval(data)


def _walk(data: Any, path: List[str]) -> Any:
    cur = data
    for seg in path:
        if isinstance(cur, dict):
            if seg in cur:
                cur = cur[seg]
                continue
            # case-insensitive fallback: our JSON uses CamelCase but
            # filters written against the reference docs sometimes use
            # the Go field name with different casing
            low = seg.lower()
            for k in cur:
                if isinstance(k, str) and k.lower() == low:
                    cur = cur[k]
                    break
            else:
                return EMPTY
        elif isinstance(cur, (list, tuple)):
            try:
                cur = cur[int(seg)]
            except (ValueError, IndexError):
                return EMPTY
        else:
            return EMPTY
    return cur


def _coerce(literal: str, field: Any) -> Any:
    """Coerce the string literal toward the field's runtime type."""
    if isinstance(field, bool):
        if literal.lower() in ("true", "false"):
            return literal.lower() == "true"
        return literal
    if isinstance(field, int) and not isinstance(field, bool):
        try:
            return int(literal)
        except ValueError:
            return literal
    if isinstance(field, float):
        try:
            return float(literal)
        except ValueError:
            return literal
    return literal


def _is_empty(v: Any) -> bool:
    if v is EMPTY or v is None:
        return True
    if isinstance(v, (str, list, tuple, dict)):
        return len(v) == 0
    return False


class _Match(_Node):
    """selector <op> value (or value in selector)."""

    def __init__(self, path: List[str], op: str, literal: Optional[str]):
        self.path = path
        self.op = op
        self.literal = literal
        if op in ("matches", "not matches") and literal is not None:
            try:
                self.rx = re.compile(literal)
            except re.error as e:
                raise BexprError(f"bad regex {literal!r}: {e}") from None

    def eval(self, data):
        field = _walk(data, self.path)
        op = self.op
        if op == "is empty":
            return _is_empty(field)
        if op == "is not empty":
            return not _is_empty(field)
        lit = self.literal
        if op in ("in", "not in", "contains", "not contains"):
            if isinstance(field, dict):
                hit = lit in field
            elif isinstance(field, (list, tuple)):
                hit = any(str(x) == lit or x == _coerce(lit, x)
                          for x in field)
            elif isinstance(field, str):
                hit = lit in field
            else:
                hit = False
            return hit if op in ("in", "contains") else not hit
        if op in ("matches", "not matches"):
            hit = isinstance(field, str) and bool(self.rx.search(field))
            return hit if op == "matches" else not hit
        # == / !=
        if field is EMPTY:
            eq = False
        else:
            want = _coerce(lit, field)
            eq = field == want or str(field) == lit
        return eq if op == "==" else not eq


class _Parser:
    def __init__(self, tokens: List[_Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[_Token]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> _Token:
        t = self.peek()
        if t is None:
            raise BexprError("unexpected end of expression")
        self.i += 1
        return t

    def expect_word(self, *words: str) -> str:
        t = self.next()
        if t.kind != "word" or t.text.lower() not in words:
            raise BexprError(f"expected {'/'.join(words)}, got {t.text!r}")
        return t.text.lower()

    # ---------------------------------------------------------- grammar

    def parse(self) -> _Node:
        node = self.or_expr()
        if self.peek() is not None:
            raise BexprError(f"trailing input at {self.peek().text!r}")
        return node

    def or_expr(self) -> _Node:
        parts = [self.and_expr()]
        while (t := self.peek()) and t.kind == "word" \
                and t.text.lower() == "or":
            self.next()
            parts.append(self.and_expr())
        return parts[0] if len(parts) == 1 else _Or(parts)

    def and_expr(self) -> _Node:
        parts = [self.unary()]
        while (t := self.peek()) and t.kind == "word" \
                and t.text.lower() == "and":
            self.next()
            parts.append(self.unary())
        return parts[0] if len(parts) == 1 else _And(parts)

    def unary(self) -> _Node:
        t = self.peek()
        if t is None:
            raise BexprError("unexpected end of expression")
        if t.kind == "word" and t.text.lower() == "not":
            self.next()
            return _Not(self.unary())
        if t.kind == "lparen":
            self.next()
            node = self.or_expr()
            tt = self.next()
            if tt.kind != "rparen":
                raise BexprError("missing )")
            return node
        return self.match()

    def selector(self) -> List[str]:
        path: List[str] = []
        t = self.next()
        if t.kind == "string":
            path.append(_unquote(t.text))
        elif t.kind == "word" and t.text.lower() not in _KEYWORDS:
            path.append(t.text)
        else:
            raise BexprError(f"expected selector, got {t.text!r}")
        while (nt := self.peek()) is not None:
            if nt.kind == "dot":
                self.next()
                seg = self.next()
                if seg.kind == "word":
                    path.append(seg.text)
                elif seg.kind == "string":
                    path.append(_unquote(seg.text))
                elif seg.kind == "number":
                    path.append(seg.text)
                else:
                    raise BexprError(
                        f"bad selector segment {seg.text!r}")
            elif nt.kind == "lbracket":
                self.next()
                seg = self.next()
                if seg.kind not in ("string", "word", "number"):
                    raise BexprError(
                        f"bad index segment {seg.text!r}")
                path.append(_unquote(seg.text)
                            if seg.kind == "string" else seg.text)
                if self.next().kind != "rbracket":
                    raise BexprError("missing ]")
            else:
                break
        return path

    def match(self) -> _Node:
        t = self.peek()
        # literal-first form: "value" in Selector / 3 in Selector
        if t is not None and t.kind in ("string", "number"):
            save = self.i
            lit_tok = self.next()
            nt = self.peek()
            if nt is not None and nt.kind == "word" \
                    and nt.text.lower() in ("in", "not"):
                neg = False
                if nt.text.lower() == "not":
                    self.next()
                    self.expect_word("in")
                    neg = True
                else:
                    self.next()
                lit = _unquote(lit_tok.text) \
                    if lit_tok.kind == "string" else lit_tok.text
                path = self.selector()
                return _Match(path, "not in" if neg else "in", lit)
            self.i = save
        path = self.selector()
        t = self.next()
        if t.kind == "op":
            return _Match(path, t.text, self.value())
        if t.kind == "word":
            w = t.text.lower()
            if w == "contains":
                return _Match(path, "contains", self.value())
            if w == "matches":
                return _Match(path, "matches", self.value())
            if w == "is":
                nt = self.next()
                if nt.kind == "word" and nt.text.lower() == "empty":
                    return _Match(path, "is empty", None)
                if nt.kind == "word" and nt.text.lower() == "not":
                    self.expect_word("empty")
                    return _Match(path, "is not empty", None)
                raise BexprError(f"expected empty, got {nt.text!r}")
            if w == "not":
                w2 = self.expect_word("contains", "matches", "in")
                if w2 == "in":
                    raise BexprError("'not in' takes the literal first")
                return _Match(path, f"not {w2}", self.value())
        raise BexprError(f"expected operator, got {t.text!r}")

    def value(self) -> str:
        t = self.next()
        if t.kind == "string":
            return _unquote(t.text)
        if t.kind in ("number", "word"):
            return t.text
        raise BexprError(f"expected value, got {t.text!r}")


class Filter:
    """Compiled filter; callable on one row, plus a list helper."""

    def __init__(self, root: _Node, src: str):
        self._root = root
        self.src = src

    def __call__(self, row: Any) -> bool:
        return self._root.eval(row)

    def filter(self, rows):
        return [r for r in rows if self._root.eval(r)]


def compile_filter(src: str) -> Filter:
    toks = _tokenize(src)
    if not toks:
        raise BexprError("empty filter expression")
    return Filter(_Parser(toks).parse(), src)
