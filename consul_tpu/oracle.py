"""GossipOracle: host-side handle on the device-resident serf pool.

The reference's agent consumes serf through an event channel + member list
(agent/consul/server_serf.go:203 lanEventHandler; agent/agent.go:1629
GetLANCoordinate).  The oracle is that interface for the TPU sim: it owns
the `ClusterState`, advances it (inline or via a pacer thread), applies
host commands (join/leave/kill/event-fire) between ticks, and answers
member/coordinate/RTT queries — the `-gossip-backend=tpu-sim` delegate of
BASELINE.json's north star.

Node naming: the sim is dense [0, N); the oracle maps names ↔ ids and
tracks which ids are provisioned (joined) so a 1M-slot pool can start
sparsely populated, like a cluster that hasn't finished joining.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import events as events_model
from consul_tpu.models import serf, swim, vivaldi


def _to_host(x) -> np.ndarray:
    """The oracle's ONE device→host seam.  Everything the oracle ever
    transfers funnels through here so (a) the O(k)-transfer contract is
    testable by spying on a single function and (b) the
    gather_discipline checker has exactly one module to reason about —
    every caller hands it a bounded page/summary, never a bare
    node-axis state leaf."""
    return np.asarray(x)


def _bucket(k: int, n: int) -> int:
    """Round a page size up to a power of two (min 8, capped at n): the
    paged read path compiles one kernel per BUCKET, not per request
    size — at most log2(N) variants ever exist (recompile hygiene).
    The cap never drops below k: a query list may exceed the pool size
    (sort_by_rtt over a service list with duplicate instances)."""
    b = 8
    while b < k:
        b *= 2
    if k <= n:
        b = min(b, max(n, 1))
    return b


def _coord_row(c, i):
    """One node's Vivaldi row from (possibly node-sharded) coordinate
    state, gather-free: row-indexing the sharded [N, D] tensor
    (`c.coords[i]`) all-gathers it under GSPMD (hlo_lint
    gather-freedom finding, ISSUE 20); the one-hot mask + sum lowers
    to local selects plus an all-reduce of [D] partials instead, and
    is exact (one row survives the mask)."""
    n = c.coords.shape[0]
    at = jnp.arange(n, dtype=jnp.int32) == i
    vec = jnp.sum(jnp.where(at[:, None], c.coords, 0.0), axis=0)

    def pick(x):
        return jnp.sum(jnp.where(at, x, 0.0))

    return vec, pick(c.error), pick(c.adjustment), pick(c.height)


class GossipOracle:
    def __init__(self, gossip: Optional[GossipConfig] = None,
                 sim: Optional[SimConfig] = None,
                 node_prefix: str = "node",
                 mesh=None):
        self.gossip = gossip or GossipConfig.lan()
        self.sim = sim or SimConfig(n_nodes=64, rumor_slots=16)
        if mesh is not None and self.sim.shard_blocks != mesh.size:
            # wire the mesh size into the ring-exchange lowering hint
            # (ops/rolls.py) so the oracle's own step compiles to
            # static collective-permutes instead of all-gathering the
            # doubled ring buffer; results are identical either way
            import dataclasses as _dc
            self.sim = _dc.replace(self.sim, shard_blocks=mesh.size)
        self.params = serf.make_params(self.gossip, self.sim)
        self._state = serf.init_state(self.params,
                                      n_initial=self.sim.n_initial)
        # optional device mesh: the pool's node axis shards across it
        # (parallel/mesh.py) and EVERY read below answers against the
        # sharded state — the paged/summary reductions replicate only
        # their [k]-bounded outputs, so no full node-axis gather ever
        # happens (the contract gather_discipline lints).
        self.mesh = mesh
        self._sharding = None
        if mesh is not None:
            from consul_tpu.parallel import mesh as meshlib
            self._sharding = meshlib.state_sharding(self._state, mesh)
            self._state = jax.device_put(self._state, self._sharding)
        self._lock = threading.RLock()
        # deliberately NOT donate_argnums: oracle readers (members
        # snapshots, the pacer's hard_sync, metrics scrapes) hold
        # references to self._state across advance() calls from other
        # threads; donation would delete those buffers under them.
        # The bench and the batch tools own their state exclusively and
        # DO donate (bench.py, tools/profile_swim.py).
        self._step = jax.jit(serf.step, static_argnums=0,
                             out_shardings=self._sharding)
        self._metrics_fn = jax.jit(serf.metrics_vector, static_argnums=0)
        self._shard_metrics_fn = jax.jit(serf.shard_metrics,
                                         static_argnums=(0, 2))
        # gather-free read kernels (bound once — recompile hygiene):
        # device-side reductions whose outputs are O(page), never O(N)
        self._counts_fn = jax.jit(serf.membership_counts, static_argnums=0)
        self._page_fn = jax.jit(serf.membership_page, static_argnums=0)
        self._delta_fn = jax.jit(serf.membership_delta,
                                 static_argnums=(0, 4))
        self._rtt_order_fn = jax.jit(serf.rtt_order, static_argnums=0)
        self._coord_row_fn = jax.jit(_coord_row)
        self._node_prefix = node_prefix
        self._names: Dict[int, str] = {
            i: f"{node_prefix}{i}" for i in range(self.sim.n_nodes)}
        self._ids: Dict[str, int] = {v: k for k, v in self._names.items()}
        # provisioned = ids that ever joined; never-joined slots of a
        # sparse pool (n_initial < n) must not appear as phantom "left"
        # members in listings (0 decodes to all-N exactly as in
        # swim.init_state — single sentinel convention)
        n_init = self.sim.n_initial or self.sim.n_nodes
        self._provisioned = np.arange(self.sim.n_nodes) < n_init
        # device mirror of the provisioned mask: summary reductions run
        # against it on device (sharded under a mesh) — updated in place
        # on spawn (one-element scatter), uploaded in full only here
        prov = jnp.asarray(self._provisioned)
        if self._sharding is not None:
            from consul_tpu.parallel import mesh as meshlib
            prov = jax.device_put(
                prov, meshlib.state_sharding(prov, mesh))
        self._prov_dev = prov
        # device-side status checkpoints, one per delta CONSUMER: the
        # public members_delta() cursor and the flight recorder's flap
        # journal each own a slot — a metrics scrape consuming the
        # journal's delta must never starve a delta client (or vice
        # versa).  None until that consumer's first call establishes it.
        self._status_ckpt = None
        self._flap_ckpt = None
        self._events: List[dict] = []           # host-side payload ring
        self._event_ring = 256                  # reference ring size
        # gossip keyring (serf keyring: install/use/remove/list — the
        # sim carries no ciphertext, but key lifecycle state is the
        # operator surface, agent/keyring.go)
        self._keyring: List[str] = []
        self._primary_key: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False

    # ------------------------------------------------------------- lifecycle

    def start(self, tick_seconds: float = 0.0) -> None:
        """Background pacer: one sim tick per `tick_seconds` of wall time
        (0 = free-running)."""
        if self._thread is not None:
            return
        self._running = True

        def loop():
            while self._running:
                t0 = time.time()
                self.advance(1)
                # bound the device queue to one in-flight tick: a free-
                # running pacer that only ever enqueues starves every
                # reader's host transfer behind an unbounded queue.
                # Block OUTSIDE the lock — readers need it while we wait
                # on the device (a superseded array still bounds the
                # queue).
                state = self._state
                from consul_tpu.utils import hard_sync
                hard_sync(state.swim.tick)
                if tick_seconds > 0:
                    time.sleep(max(0.0, tick_seconds - (time.time() - t0)))
                else:
                    time.sleep(0)   # yield: readers need lock windows

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def advance(self, n_ticks: int = 1) -> None:
        from consul_tpu.profiler import default_profiler
        prof = default_profiler()
        t0 = time.perf_counter()
        with self._lock:
            s = self._state
            for _ in range(n_ticks):
                s = self._step(self.params, s)
            self._state = s
        # always-on tick profile: per-tick dispatch EMA + the recompile
        # watchdog, both OUTSIDE the oracle lock (note_cache_size may
        # emit telemetry + a flight event on an unexpected recompile)
        prof.observe("oracle.advance",
                     (time.perf_counter() - t0) / max(1, n_ticks))
        prof.note_jit("oracle.step", self._step)

    def warmup(self) -> None:
        """Precompile the mutating kernels (rejoin/leave/kill + a tick)
        at the current pool shape, discarding results.  A delegate
        client's first join/leave otherwise pays the XLA compile
        (~tens of seconds tunneled) inside ITS request timeout and
        fails the call; the bridge triggers this before accepting."""
        import jax
        with self._lock:
            s = self._state
            for out in (swim.rejoin(self.params.swim, s.swim, 0),
                        swim.leave(self.params.swim, s.swim, 0),
                        swim.kill(s.swim, 0),
                        self._step(self.params, s),
                        # the metrics summary too: the FIRST /v1/agent/
                        # metrics scrape otherwise pays this compile
                        # inside its HTTP request while holding the
                        # oracle lock (blocking every tick/join behind
                        # it for the compile duration)
                        self._metrics_fn(self.params, s)):
                jax.block_until_ready(out)
        # the paged member read and the summary reduction are every
        # client's FIRST reads — compile both (they carry no cache to
        # invalidate: each call answers against current state)
        try:
            self.members(limit=1)
            self.members_summary()
        except Exception:
            pass

    # -------------------------------------------------------------- identity

    def node_id(self, name: str) -> int:
        """Resolve a PROVISIONED member's id; never-joined default
        names of a sparse pool don't resolve (they would read as
        phantom 'left' members — listings hide them, so point lookups
        must too)."""
        i = self._ids[name]
        if not self._provisioned[i]:
            raise KeyError(name)
        return i

    def node_name(self, node_id: int) -> str:
        return self._names.get(node_id, f"{self._node_prefix}{node_id}")

    # ------------------------------------------------------------ membership

    _STATUS_NAMES = ("alive", "failed", "left")

    def _page(self, ids: np.ndarray):
        """Gather (status, incarnation, up) rows for `ids` via one
        jitted device gather padded to a power-of-two bucket; transfers
        O(len(ids)), never O(N)."""
        k = len(ids)
        bucket = _bucket(k, self.sim.n_nodes)
        padded = np.zeros(bucket, np.int32)
        padded[:k] = ids
        with self._lock:
            st, inc, up = self._page_fn(self.params, self._state,
                                        jnp.asarray(padded))
        return (_to_host(st)[:k], _to_host(inc)[:k], _to_host(up)[:k])

    def members(self, limit: Optional[int] = None,
                offset: int = 0) -> List[dict]:
        """Serf member list with statuses (alive/failed/left), oracle view.

        Paginated AND gather-free: the requested page's ids are gathered
        on device and only those rows transfer — a members(limit=k) call
        against a 1M-slot (possibly multi-device-sharded) pool moves
        O(k) bytes to host."""
        ids = np.flatnonzero(self._provisioned)
        n = len(ids)
        offset = max(0, offset)
        end = n if limit is None else min(offset + max(0, limit), n)
        page_ids = ids[offset:end]
        if len(page_ids) == 0:
            return []
        status, inc, up = self._page(page_ids)
        names = self._STATUS_NAMES
        return [{"name": self.node_name(int(i)), "id": int(i),
                 "status": names[status[j]], "incarnation": int(inc[j]),
                 "actually_up": bool(up[j])}
                for j, i in enumerate(page_ids)]

    def members_summary(self) -> Dict[str, int]:
        """Counts by status — one jitted device reduction over the
        provisioned mask, 16 bytes transferred regardless of N; serves
        the /v1/agent/metrics membership gauges (the reference's usage
        metrics role, agent/consul/usagemetrics/)."""
        with self._lock:
            counts = self._counts_fn(self.params, self._state,
                                     self._prov_dev)
        alive, failed, left, total = (int(v) for v in _to_host(counts))
        return {"alive": alive, "failed": failed, "left": left,
                "total": total}

    def _delta_read(self, ckpt_attr: str, max_changes: int) -> dict:
        """Shared incremental-delta body against a NAMED checkpoint
        slot (atomic check-read-advance under the oracle lock).
        Returns {"count", "changed", "truncated", "page", "first"} —
        `page` is the power-of-two row budget actually used, `first`
        marks the checkpoint-establishing call."""
        k = _bucket(max(1, max_changes), self.sim.n_nodes)
        with self._lock:
            prev = getattr(self, ckpt_attr)
            first = prev is None
            if first:
                # no checkpoint yet: everything differs from the
                # impossible status -1
                prev = jnp.full((self.sim.n_nodes,), -1, jnp.int8)
                if self._sharding is not None:
                    from consul_tpu.parallel import mesh as meshlib
                    prev = jax.device_put(
                        prev, meshlib.state_sharding(prev, self.mesh))
            st, n_changed, idx, states = self._delta_fn(
                self.params, self._state, prev, self._prov_dev, k)
            setattr(self, ckpt_attr, st)
        n_changed = int(n_changed)
        idx = _to_host(idx)
        states = _to_host(states)
        names = self._STATUS_NAMES
        changed = [(int(i), names[states[j]])
                   for j, i in enumerate(idx) if i >= 0]
        return {"count": n_changed, "changed": changed,
                "truncated": n_changed > k, "page": k, "first": first}

    def members_delta(self, max_changes: int = 256) -> dict:
        """Changed members since the last delta checkpoint — the
        incremental device→control-plane read (ROADMAP item 5): a pool
        with F flaps since the last call moves min(F, max_changes)
        rows, not a full gather.  Returns {"count", "changed":
        [(id, status_name)...], "truncated"}; on truncation (count >
        the page budget) callers fall back to the paged listing.  The
        first call reports every provisioned member as changed (no
        checkpoint yet).  This cursor is independent of the flight
        recorder's (journal_flaps) — a metrics scrape never consumes a
        delta client's pending changes."""
        d = self._delta_read("_status_ckpt", max_changes)
        return {"count": d["count"], "changed": d["changed"],
                "truncated": d["truncated"]}

    def status(self, name: str) -> str:
        i = self.node_id(name)
        status, _, _ = self._page(np.array([i], np.int32))
        return self._STATUS_NAMES[int(status[0])]

    def believed_down_fraction(self, name: str) -> float:
        with self._lock:
            return float(swim.believed_down_fraction(
                self.params.swim, self._state.swim, self.node_id(name)))

    def kill(self, name: str) -> None:
        # no read-cache invalidation needed: the paged/summary reads
        # answer against current device state on every call
        with self._lock:
            self._state = self._state.replace(
                swim=swim.kill(self._state.swim, self.node_id(name)))

    def revive(self, name: str) -> None:
        """Restart + rejoin: heals even a committed death (the node comes
        back with a higher incarnation and refutes — memberlist rejoin)."""
        with self._lock:
            self._state = self._state.replace(
                swim=swim.rejoin(self.params.swim, self._state.swim,
                                 self.node_id(name)))

    def leave(self, name: str) -> None:
        with self._lock:
            self._state = self._state.replace(
                swim=swim.leave(self.params.swim, self._state.swim,
                                self.node_id(name)))

    def spawn(self, name: Optional[str] = None) -> str:
        """Elastic join of a NEW node: claim the first unprovisioned
        slot (SimConfig.n_initial leaves free ids), optionally name
        it, and rejoin it into the pool (memberlist Join — the cluster
        learns of it via the alive rumor).  Raises RuntimeError when
        the pool is full."""
        with self._lock:
            i = None
            if name is not None and name in self._ids:
                j = self._ids[name]
                if self._provisioned[j]:
                    raise ValueError(f"node name {name!r} in use")
                # the default name of an unprovisioned slot claims THAT
                # slot — otherwise the name would be simultaneously
                # "nonexistent" (node_id) and "taken" (here)
                i = j
            if i is None:
                free = np.flatnonzero(~self._provisioned)
                if len(free) == 0:
                    raise RuntimeError(
                        "pool full: no unprovisioned slots")
                i = int(free[0])
            if name is not None and self._names[i] != name:
                old = self._names[i]
                self._ids.pop(old, None)
                self._names[i] = name
                self._ids[name] = i
            # ordering discipline: update device state BEFORE flipping
            # the provisioned mask — a concurrent reader pairing the
            # OLD mask with the new state merely misses the new node,
            # never reports it as a phantom "left"
            self._state = self._state.replace(
                swim=swim.rejoin(self.params.swim, self._state.swim, i))
            # one-element device scatter keeps the mirror sharded in
            # place — never a full host→device re-upload of the mask
            self._prov_dev = self._prov_dev.at[i].set(True)
            self._provisioned[i] = True
            return self._names[i]

    @property
    def provisioned_count(self) -> int:
        """Members that ever joined (the listing length)."""
        return int(self._provisioned.sum())

    # ----------------------------------------------------------- coordinates

    def coordinate(self, name: str) -> dict:
        """One member's Vivaldi coordinate — a single jitted row gather
        (O(D) transfer), answered against sharded state unchanged."""
        i = self.node_id(name)
        with self._lock:
            vec, err, adj, height = self._coord_row_fn(
                self._state.coords, jnp.int32(i))
        return {"node": name,
                "vec": _to_host(vec).tolist(),
                "error": float(err),
                "adjustment": float(adj),
                "height": float(height)}

    def rtt(self, a: str, b: str) -> float:
        """Estimated RTT seconds (consul rtt command — lib/rtt.go:13)."""
        ia, ib = self.node_id(a), self.node_id(b)
        with self._lock:
            return float(vivaldi.estimate_rtt(
                self._state.coords,
                jnp.array([ia], jnp.int32), jnp.array([ib], jnp.int32))[0])

    def sort_by_rtt(self, origin: str, names: List[str]) -> List[str]:
        """?near= ordering (agent/consul/rtt.go:196) — the distance
        computation and argsort run ON DEVICE (serf.rtt_order,
        estimate_rtt semantics lib/rtt.go:13-43) against whatever
        sharding the coordinate state carries; the only transfer is the
        O(k) order vector, never the [N, D] coordinate tensor.  Query
        ids pad to a power-of-two bucket so the kernel compiles at most
        log2(N) times."""
        if not names:
            return []
        io = self.node_id(origin)
        ids = np.array([self.node_id(n) for n in names], np.int32)
        k = len(ids)
        bucket = _bucket(k, self.sim.n_nodes)
        padded = np.zeros(bucket, np.int32)
        padded[:k] = ids
        valid = np.arange(bucket) < k
        with self._lock:
            order = self._rtt_order_fn(self.params, self._state,
                                       jnp.int32(io),
                                       jnp.asarray(padded),
                                       jnp.asarray(valid))
        order = _to_host(order)
        return [names[i] for i in order if i < k]

    # ---------------------------------------------------------------- events

    _event_seq = 0

    def fire_event(self, name: str, payload: bytes, origin: str) -> str:
        """UserEvent (agent/user_event.go:23): host keeps the payload ring,
        the device disseminates the id.

        Ids come from a monotonic counter, NOT the ring length — once
        the 256-entry ring trims, a length-derived id would repeat
        forever and any since-cursor consumer (delegate
        get_broadcasts) would go permanently silent."""
        with self._lock:
            self._event_seq += 1
            eid = self._event_seq
            self._state = serf.fire_event(self.params, self._state,
                                          self.node_id(origin), eid)
            ltime = int(self._state.events.e_ltime[
                int(jnp.argmax(self._state.events.e_id == eid))])
            rec = {"id": eid, "name": name, "payload": payload,
                   "ltime": ltime, "origin": origin}
            self._events.append(rec)
            if len(self._events) > self._event_ring:
                self._events = self._events[-self._event_ring:]
        # journal OUTSIDE the oracle lock; the trace id rides in from
        # the HTTP entry contextvar so a /v1/event/fire shows up in
        # /v1/agent/events and monitor streams correlated to its
        # request trace (user_event.go → flight recorder)
        from consul_tpu import flight
        flight.emit("serf.user_event",
                    labels={"name": name, "origin": origin,
                            "id": eid, "ltime": ltime})
        return str(eid)

    def event_list(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def event_coverage(self, event_id: int) -> float:
        with self._lock:
            st = self._state
            slots = np.asarray(st.events.e_id)
            hit = np.nonzero(slots == event_id)[0]
            if len(hit) == 0:
                return 1.0  # expired ⇒ fully disseminated window passed
            return float(events_model.coverage(
                self.params.events, st.events, int(hit[0]),
                st.swim.up, st.swim.member))

    # --------------------------------------------------------------- keyring

    def keyring_list(self) -> dict:
        with self._lock:
            return {"Keys": {k: self.sim.n_nodes for k in self._keyring},
                    "PrimaryKeys": ({self._primary_key: self.sim.n_nodes}
                                    if self._primary_key else {}),
                    "NumNodes": self.sim.n_nodes}

    def keyring_install(self, key: str) -> None:
        # validate BEFORE storing: a malformed key that became primary
        # would wedge the delegate socket (no client could ever form a
        # frame the codec accepts) — same check as boot-time `encrypt`
        from consul_tpu.gossip_crypto import _decode_key
        _decode_key(key)
        with self._lock:
            if key not in self._keyring:
                self._keyring.append(key)
            if self._primary_key is None:
                self._primary_key = key

    def keyring_use(self, key: str) -> None:
        with self._lock:
            if key not in self._keyring:
                raise KeyError(f"key not installed")
            self._primary_key = key

    def keyring_remove(self, key: str) -> None:
        with self._lock:
            if key == self._primary_key:
                raise ValueError("cannot remove the primary key")
            if key in self._keyring:
                self._keyring.remove(key)

    # --------------------------------------------------------------- metrics

    def sim_metrics(self) -> Dict[str, float]:
        """Device-side sim telemetry as {name: value} (swim.METRIC_NAMES).

        This is a host-sync CHECKPOINT: one jitted reduction over state
        the device already holds, one small transfer — the per-tick
        accumulation rides SwimState.ctr inside the step, so the hot
        loop never pays a host round-trip for metrics."""
        from consul_tpu.profiler import default_profiler
        with default_profiler().span("oracle.metrics"):
            with self._lock:
                vec = self._metrics_fn(self.params, self._state)
            vals = _to_host(vec)
        return {name: float(v)
                for name, v in zip(swim.METRIC_NAMES, vals)}

    def shard_metrics(self) -> Dict[int, Dict[str, float]]:
        """Per-shard device telemetry: swim.SHARD_METRIC_NAMES gauges
        for each of the `shard_blocks` node-axis blocks (the mesh
        shards under a device mesh), one [B, K] transfer.  Empty when
        the pool is unsharded or N doesn't split evenly."""
        blocks = self.sim.shard_blocks
        if blocks <= 1 or self.sim.n_nodes % blocks:
            return {}
        with self._lock:
            mat = self._shard_metrics_fn(self.params, self._state,
                                         blocks)
        mat = _to_host(mat)
        return {b: {name: float(v)
                    for name, v in zip(swim.SHARD_METRIC_NAMES, mat[b])}
                for b in range(blocks)}

    def journal_flaps(self, max_changes: int = 256) -> int:
        """Membership flap events for the flight recorder, derived from
        the incremental delta (ROADMAP item 5 seam) against the
        journal's OWN checkpoint: F flaps since the last call journal
        min(F, page) rows and move that many rows over the device→host
        seam — never a node-axis gather.  The first call only
        establishes the checkpoint (journaling a whole pool as
        'flapped' would be noise, not signal).  When more members
        flapped than the page holds, the fetched rows are journaled
        anyway and one `serf.flap.truncated` warning records the true
        count — a mass-failure timeline keeps the identities it paid
        to transfer.  Returns the number of flap rows journaled."""
        from consul_tpu import flight
        d = self._delta_read("_flap_ckpt", max_changes)
        if d["first"]:
            return 0
        tick = self.tick
        # trace_id explicitly EMPTY: a flap is cluster state, not an
        # artifact of whichever request's scrape happened to surface it
        # — inheriting the contextvar would stamp membership changes
        # with a random GET /v1/agent/metrics trace
        if d["truncated"]:
            flight.emit("serf.flap.truncated",
                        labels={"count": d["count"],
                                "limit": d["page"], "tick": tick},
                        trace_id="")
        for i, status in d["changed"]:
            flight.emit("serf.member.flap",
                        labels={"node": self.node_name(int(i)),
                                "status": status, "tick": tick},
                        trace_id="")
        return len(d["changed"])

    def publish_sim_metrics(self, registry=None) -> Dict[str, float]:
        """Surface sim_metrics() as consul.serf.* gauges (the reference's
        serf/memberlist go-metrics names land under consul.serf/
        consul.memberlist; the sim's single pool maps to consul.serf).

        This call is a host-sync CHECKPOINT, so it also (a) publishes
        the per-shard split of the pool gauges as consul.serf.*{shard}
        plus cross-shard skew/imbalance, and (b) feeds the flight
        recorder's membership-flap journal from the incremental delta
        — O(flaps) rows per scrape, the device plane's event feed."""
        from consul_tpu import telemetry
        reg = registry or telemetry.default_registry()
        m = self.sim_metrics()
        for name, v in m.items():
            reg.set_gauge(("serf",) + tuple(name.split(".")), v)
        shards = self.shard_metrics()
        if shards:
            for b, row in shards.items():
                for name, v in row.items():
                    reg.set_gauge(("serf",) + tuple(name.split(".")),
                                  v, labels={"shard": str(b)})
            alive = [row["members.alive"] for row in shards.values()]
            mean = sum(alive) / len(alive)
            # skew: spread of live membership across shards relative to
            # the mean (0 = perfectly balanced); imbalance: the hottest
            # shard's load factor — the signal that one device carries
            # disproportionate gossip state
            reg.set_gauge(("serf", "shard", "skew"),
                          (max(alive) - min(alive)) / mean
                          if mean else 0.0)
            reg.set_gauge(("serf", "shard", "imbalance"),
                          max(alive) / mean if mean else 0.0)
        self.journal_flaps()
        return m

    # ------------------------------------------------------------------ misc

    @property
    def tick(self) -> int:
        with self._lock:
            return int(self._state.swim.tick)

    @property
    def n_nodes(self) -> int:
        return self.sim.n_nodes
