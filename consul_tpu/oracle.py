"""GossipOracle: host-side handle on the device-resident serf pool.

The reference's agent consumes serf through an event channel + member list
(agent/consul/server_serf.go:203 lanEventHandler; agent/agent.go:1629
GetLANCoordinate).  The oracle is that interface for the TPU sim: it owns
the `ClusterState`, advances it (inline or via a pacer thread), applies
host commands (join/leave/kill/event-fire) between ticks, and answers
member/coordinate/RTT queries — the `-gossip-backend=tpu-sim` delegate of
BASELINE.json's north star.

Node naming: the sim is dense [0, N); the oracle maps names ↔ ids and
tracks which ids are provisioned (joined) so a 1M-slot pool can start
sparsely populated, like a cluster that hasn't finished joining.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import events as events_model
from consul_tpu.models import serf, swim, vivaldi


class GossipOracle:
    def __init__(self, gossip: Optional[GossipConfig] = None,
                 sim: Optional[SimConfig] = None,
                 node_prefix: str = "node"):
        self.gossip = gossip or GossipConfig.lan()
        self.sim = sim or SimConfig(n_nodes=64, rumor_slots=16)
        self.params = serf.make_params(self.gossip, self.sim)
        self._state = serf.init_state(self.params,
                                      n_initial=self.sim.n_initial)
        self._lock = threading.RLock()
        # deliberately NOT donate_argnums: oracle readers (members
        # snapshots, the pacer's hard_sync, metrics scrapes) hold
        # references to self._state across advance() calls from other
        # threads; donation would delete those buffers under them.
        # The bench and the batch tools own their state exclusively and
        # DO donate (bench.py, tools/profile_swim.py).
        self._step = jax.jit(serf.step, static_argnums=0)
        self._metrics_fn = jax.jit(serf.metrics_vector, static_argnums=0)
        self._node_prefix = node_prefix
        self._names: Dict[int, str] = {
            i: f"{node_prefix}{i}" for i in range(self.sim.n_nodes)}
        self._ids: Dict[str, int] = {v: k for k, v in self._names.items()}
        # provisioned = ids that ever joined; never-joined slots of a
        # sparse pool (n_initial < n) must not appear as phantom "left"
        # members in listings (0 decodes to all-N exactly as in
        # swim.init_state — single sentinel convention)
        n_init = self.sim.n_initial or self.sim.n_nodes
        self._provisioned = np.arange(self.sim.n_nodes) < n_init
        self._events: List[dict] = []           # host-side payload ring
        self._event_ring = 256                  # reference ring size
        # gossip keyring (serf keyring: install/use/remove/list — the
        # sim carries no ciphertext, but key lifecycle state is the
        # operator surface, agent/keyring.go)
        self._keyring: List[str] = []
        self._primary_key: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False

    # ------------------------------------------------------------- lifecycle

    def start(self, tick_seconds: float = 0.0) -> None:
        """Background pacer: one sim tick per `tick_seconds` of wall time
        (0 = free-running)."""
        if self._thread is not None:
            return
        self._running = True

        def loop():
            while self._running:
                t0 = time.time()
                self.advance(1)
                # bound the device queue to one in-flight tick: a free-
                # running pacer that only ever enqueues starves every
                # reader's host transfer behind an unbounded queue.
                # Block OUTSIDE the lock — readers need it while we wait
                # on the device (a superseded array still bounds the
                # queue).
                state = self._state
                from consul_tpu.utils import hard_sync
                hard_sync(state.swim.tick)
                if tick_seconds > 0:
                    time.sleep(max(0.0, tick_seconds - (time.time() - t0)))
                else:
                    time.sleep(0)   # yield: readers need lock windows

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def advance(self, n_ticks: int = 1) -> None:
        with self._lock:
            s = self._state
            for _ in range(n_ticks):
                s = self._step(self.params, s)
            self._state = s

    def warmup(self) -> None:
        """Precompile the mutating kernels (rejoin/leave/kill + a tick)
        at the current pool shape, discarding results.  A delegate
        client's first join/leave otherwise pays the XLA compile
        (~tens of seconds tunneled) inside ITS request timeout and
        fails the call; the bridge triggers this before accepting."""
        import jax
        with self._lock:
            s = self._state
            for out in (swim.rejoin(self.params.swim, s.swim, 0),
                        swim.leave(self.params.swim, s.swim, 0),
                        swim.kill(s.swim, 0),
                        self._step(self.params, s),
                        # the metrics summary too: the FIRST /v1/agent/
                        # metrics scrape otherwise pays this compile
                        # inside its HTTP request while holding the
                        # oracle lock (blocking every tick/join behind
                        # it for the compile duration)
                        self._metrics_fn(self.params, s)):
                jax.block_until_ready(out)
        # the members/down-mask computation is every client's FIRST
        # read — compile it too, then drop the snapshot it cached so
        # later reads re-evaluate against current state
        try:
            self.members(limit=1)
        except Exception:
            pass
        self.__dict__.pop("_member_snap", None)

    # -------------------------------------------------------------- identity

    def node_id(self, name: str) -> int:
        """Resolve a PROVISIONED member's id; never-joined default
        names of a sparse pool don't resolve (they would read as
        phantom 'left' members — listings hide them, so point lookups
        must too)."""
        i = self._ids[name]
        if not self._provisioned[i]:
            raise KeyError(name)
        return i

    def node_name(self, node_id: int) -> str:
        return self._names.get(node_id, f"{self._node_prefix}{node_id}")

    # ------------------------------------------------------------ membership

    def _members_host(self, max_age: float = 1.0):
        """Host-side numpy snapshot of membership state (statuses 0=alive
        1=failed 2=left, incarnation, up), refreshed at most every
        `max_age` seconds — serving paths must not pay a device round-trip
        or an O(N) python loop per request (VERDICT r1 weak #6)."""
        now = time.monotonic()
        snap = self.__dict__.get("_member_snap")
        if snap is not None and now - snap[0] < max_age:
            return snap[1]
        with self._lock:
            st = self._state.swim
            up = np.asarray(st.up)
            member = np.asarray(st.member)
            dead = np.asarray(self._oracle_down_mask())
            left = np.asarray(st.committed_left) | ~member
            inc = np.asarray(st.incarnation)
            status = np.zeros(len(up), np.int8)
            status[dead] = 1
            status[left] = 2      # left wins over failed (serf precedence)
            host = (status, inc, up)
            # store under the lock: a kill() invalidation must not be
            # overwritten by a reader re-caching pre-mutation state
            self.__dict__["_member_snap"] = (now, host)
        return host

    _STATUS_NAMES = ("alive", "failed", "left")

    def members(self, limit: Optional[int] = None,
                offset: int = 0) -> List[dict]:
        """Serf member list with statuses (alive/failed/left), oracle view.

        Paginated: python dicts are built only for the requested page —
        the full status computation is vectorized numpy on a cached
        snapshot, so this works at the N the sim targets."""
        status, inc, up = self._members_host()
        ids = np.flatnonzero(self._provisioned)
        n = len(ids)
        offset = max(0, offset)
        end = n if limit is None else min(offset + max(0, limit), n)
        names = self._STATUS_NAMES
        return [{"name": self.node_name(i), "id": int(i),
                 "status": names[status[i]], "incarnation": int(inc[i]),
                 "actually_up": bool(up[i])}
                for i in ids[offset:end]]

    def members_summary(self) -> Dict[str, int]:
        """Counts by status — O(N) numpy, no per-node dicts; serves the
        /v1/agent/metrics membership gauges (the reference's usage
        metrics role, agent/consul/usagemetrics/)."""
        status, _, _ = self._members_host()
        counts = np.bincount(status[self._provisioned], minlength=3)
        return {"alive": int(counts[0]), "failed": int(counts[1]),
                "left": int(counts[2]),
                "total": int(self._provisioned.sum())}

    def _oracle_down_mask(self) -> jnp.ndarray:
        """Nodes the cluster (majority view) considers failed: committed dead
        or an active dead rumor."""
        st = self._state.swim
        u = self.params.swim.rumor_slots
        dead_rumor = jnp.zeros_like(st.committed_dead).at[
            jnp.where(st.r_active & (st.r_kind == swim.DEAD), st.r_subject, 0)
        ].max(st.r_active & (st.r_kind == swim.DEAD))
        return st.committed_dead | dead_rumor

    def status(self, name: str) -> str:
        i = self.node_id(name)
        status, _, _ = self._members_host()
        if i >= len(status):
            raise KeyError(name)
        return self._STATUS_NAMES[status[i]]

    def believed_down_fraction(self, name: str) -> float:
        with self._lock:
            return float(swim.believed_down_fraction(
                self.params.swim, self._state.swim, self.node_id(name)))

    def kill(self, name: str) -> None:
        with self._lock:
            self.__dict__.pop("_member_snap", None)
            self._state = self._state.replace(
                swim=swim.kill(self._state.swim, self.node_id(name)))

    def revive(self, name: str) -> None:
        """Restart + rejoin: heals even a committed death (the node comes
        back with a higher incarnation and refutes — memberlist rejoin)."""
        with self._lock:
            self.__dict__.pop("_member_snap", None)
            self._state = self._state.replace(
                swim=swim.rejoin(self.params.swim, self._state.swim,
                                 self.node_id(name)))

    def leave(self, name: str) -> None:
        with self._lock:
            self.__dict__.pop("_member_snap", None)
            self._state = self._state.replace(
                swim=swim.leave(self.params.swim, self._state.swim,
                                self.node_id(name)))

    def spawn(self, name: Optional[str] = None) -> str:
        """Elastic join of a NEW node: claim the first unprovisioned
        slot (SimConfig.n_initial leaves free ids), optionally name
        it, and rejoin it into the pool (memberlist Join — the cluster
        learns of it via the alive rumor).  Raises RuntimeError when
        the pool is full."""
        with self._lock:
            i = None
            if name is not None and name in self._ids:
                j = self._ids[name]
                if self._provisioned[j]:
                    raise ValueError(f"node name {name!r} in use")
                # the default name of an unprovisioned slot claims THAT
                # slot — otherwise the name would be simultaneously
                # "nonexistent" (node_id) and "taken" (here)
                i = j
            if i is None:
                free = np.flatnonzero(~self._provisioned)
                if len(free) == 0:
                    raise RuntimeError(
                        "pool full: no unprovisioned slots")
                i = int(free[0])
            if name is not None and self._names[i] != name:
                old = self._names[i]
                self._ids.pop(old, None)
                self._names[i] = name
                self._ids[name] = i
            # invalidation discipline (_members_host comment): drop the
            # snapshot and update device state BEFORE flipping the
            # provisioned mask — a concurrent reader pairing the OLD
            # mask with the new snapshot merely misses the new node,
            # never reports it as a phantom "left"
            self.__dict__.pop("_member_snap", None)
            self._state = self._state.replace(
                swim=swim.rejoin(self.params.swim, self._state.swim, i))
            self._provisioned[i] = True
            return self._names[i]

    @property
    def provisioned_count(self) -> int:
        """Members that ever joined (the listing length)."""
        return int(self._provisioned.sum())

    # ----------------------------------------------------------- coordinates

    def coordinate(self, name: str) -> dict:
        i = self.node_id(name)
        with self._lock:
            c = self._state.coords
            return {"node": name,
                    "vec": np.asarray(c.coords[i]).tolist(),
                    "error": float(c.error[i]),
                    "adjustment": float(c.adjustment[i]),
                    "height": float(c.height[i])}

    def rtt(self, a: str, b: str) -> float:
        """Estimated RTT seconds (consul rtt command — lib/rtt.go:13)."""
        ia, ib = self.node_id(a), self.node_id(b)
        with self._lock:
            return float(vivaldi.estimate_rtt(
                self._state.coords,
                jnp.array([ia], jnp.int32), jnp.array([ib], jnp.int32))[0])

    def _coords_host(self, max_age: float = 1.0):
        """Host-side numpy snapshot of the coordinate state, refreshed at
        most every `max_age` seconds.  Serving paths (DNS ?near sorting,
        /v1/coordinate) must not pay a device round-trip per request —
        coordinates drift on gossip timescales, so a ~1s-stale view is
        well inside Vivaldi's own error."""
        import time as _time
        now = _time.monotonic()
        snap = self.__dict__.get("_coord_snap")
        if snap is not None and now - snap[0] < max_age:
            return snap[1]
        with self._lock:
            c = self._state.coords
            host = (np.asarray(c.coords), np.asarray(c.height),
                    np.asarray(c.adjustment))
        self.__dict__["_coord_snap"] = (now, host)
        return host

    def sort_by_rtt(self, origin: str, names: List[str]) -> List[str]:
        """?near= ordering (agent/consul/rtt.go:196) — numpy on the cached
        coordinate snapshot (estimate_rtt semantics, lib/rtt.go:13-43)."""
        coords, height, adj = self._coords_host()
        io = self.node_id(origin)
        ids = np.array([self.node_id(n) for n in names], np.int32)
        if io >= len(coords) or (len(ids) and ids.max() >= len(coords)):
            # node registered after the <=1s-stale snapshot: refresh it
            # rather than IndexError into a 500/SERVFAIL (advisor finding)
            self.__dict__.pop("_coord_snap", None)
            coords, height, adj = self._coords_host()
            keep = ids < len(coords)
            if io >= len(coords) or not keep.all():
                return list(names)  # fall back to given order
        diff = coords[ids] - coords[io]
        d = np.linalg.norm(diff, axis=-1) + height[ids] + height[io]
        adjusted = d + adj[ids] + adj[io]
        dist = np.where(adjusted > 0.0, adjusted, d)
        order = np.argsort(dist, kind="stable")
        return [names[i] for i in order]

    # ---------------------------------------------------------------- events

    _event_seq = 0

    def fire_event(self, name: str, payload: bytes, origin: str) -> str:
        """UserEvent (agent/user_event.go:23): host keeps the payload ring,
        the device disseminates the id.

        Ids come from a monotonic counter, NOT the ring length — once
        the 256-entry ring trims, a length-derived id would repeat
        forever and any since-cursor consumer (delegate
        get_broadcasts) would go permanently silent."""
        with self._lock:
            self._event_seq += 1
            eid = self._event_seq
            self._state = serf.fire_event(self.params, self._state,
                                          self.node_id(origin), eid)
            ltime = int(self._state.events.e_ltime[
                int(jnp.argmax(self._state.events.e_id == eid))])
            rec = {"id": eid, "name": name, "payload": payload,
                   "ltime": ltime, "origin": origin}
            self._events.append(rec)
            if len(self._events) > self._event_ring:
                self._events = self._events[-self._event_ring:]
            return str(eid)

    def event_list(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def event_coverage(self, event_id: int) -> float:
        with self._lock:
            st = self._state
            slots = np.asarray(st.events.e_id)
            hit = np.nonzero(slots == event_id)[0]
            if len(hit) == 0:
                return 1.0  # expired ⇒ fully disseminated window passed
            return float(events_model.coverage(
                self.params.events, st.events, int(hit[0]),
                st.swim.up, st.swim.member))

    # --------------------------------------------------------------- keyring

    def keyring_list(self) -> dict:
        with self._lock:
            return {"Keys": {k: self.sim.n_nodes for k in self._keyring},
                    "PrimaryKeys": ({self._primary_key: self.sim.n_nodes}
                                    if self._primary_key else {}),
                    "NumNodes": self.sim.n_nodes}

    def keyring_install(self, key: str) -> None:
        # validate BEFORE storing: a malformed key that became primary
        # would wedge the delegate socket (no client could ever form a
        # frame the codec accepts) — same check as boot-time `encrypt`
        from consul_tpu.gossip_crypto import _decode_key
        _decode_key(key)
        with self._lock:
            if key not in self._keyring:
                self._keyring.append(key)
            if self._primary_key is None:
                self._primary_key = key

    def keyring_use(self, key: str) -> None:
        with self._lock:
            if key not in self._keyring:
                raise KeyError(f"key not installed")
            self._primary_key = key

    def keyring_remove(self, key: str) -> None:
        with self._lock:
            if key == self._primary_key:
                raise ValueError("cannot remove the primary key")
            if key in self._keyring:
                self._keyring.remove(key)

    # --------------------------------------------------------------- metrics

    def sim_metrics(self) -> Dict[str, float]:
        """Device-side sim telemetry as {name: value} (swim.METRIC_NAMES).

        This is a host-sync CHECKPOINT: one jitted reduction over state
        the device already holds, one small transfer — the per-tick
        accumulation rides SwimState.ctr inside the step, so the hot
        loop never pays a host round-trip for metrics."""
        with self._lock:
            vec = self._metrics_fn(self.params, self._state)
        vals = np.asarray(vec)
        return {name: float(v)
                for name, v in zip(swim.METRIC_NAMES, vals)}

    def publish_sim_metrics(self, registry=None) -> Dict[str, float]:
        """Surface sim_metrics() as consul.serf.* gauges (the reference's
        serf/memberlist go-metrics names land under consul.serf/
        consul.memberlist; the sim's single pool maps to consul.serf)."""
        from consul_tpu import telemetry
        reg = registry or telemetry.default_registry()
        m = self.sim_metrics()
        for name, v in m.items():
            reg.set_gauge(("serf",) + tuple(name.split(".")), v)
        return m

    # ------------------------------------------------------------------ misc

    @property
    def tick(self) -> int:
        with self._lock:
            return int(self._state.swim.tick)

    @property
    def n_nodes(self) -> int:
        return self.sim.n_nodes
