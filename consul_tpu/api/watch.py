"""Watch plans: long-poll loops over every watchable query type.

The reference's watch package (api/watch/watch.go:21 Parse, :132 the
per-type watcher funcs) drives blocking queries in a loop and invokes a
handler on every index change; `consul watch` and the agent's `watches`
config both ride it.  Types: key, keyprefix, services, nodes, service,
checks, event, connect_roots, connect_leaf, agent_service (the last
three are the funcs.go connectRootsWatch/connectLeafWatch/
agentServiceWatch tail — VERDICT r5).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

Handler = Callable[[int, Any], None]


class WatchPlan:
    def __init__(self, client, watch_type: str, wait: str = "30s",
                 **params: Any):
        if watch_type not in WATCH_FUNCS:
            raise ValueError(
                f"unsupported watch type {watch_type!r}; "
                f"one of {sorted(WATCH_FUNCS)}")
        missing = [r for r in REQUIRED_PARAMS[watch_type]
                   if not params.get(r)]
        if missing:
            raise ValueError(
                f"watch type {watch_type!r} requires "
                f"{', '.join('-' + m for m in missing)}")
        self.client = client
        self.type = watch_type
        self.params = params
        self.wait = wait
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self, handler: Handler,
            max_events: Optional[int] = None) -> int:
        """Blocking loop: handler(index, result) on each change; returns
        the number of events delivered."""
        fetch = WATCH_FUNCS[self.type]
        index: Optional[int] = None
        delivered = 0
        last = object()
        backoff = 0.5
        while not self._stop.is_set():
            try:
                result, new_index = fetch(self.client, index, self.wait,
                                          self.params)
                backoff = 0.5
            except Exception as e:
                # transient failure (agent restart, momentary 500): the
                # reference's watch loop retries with backoff instead of
                # dying (watch.go run loop) — counted so a flapping
                # agent shows up in consul.watch.retry.  A 429 carries
                # the limiter's Retry-After hint: honor it (capped,
                # jittered) so parked watchers drain the overload they
                # are part of instead of re-offering it
                from consul_tpu import telemetry
                telemetry.incr_counter(("watch", "retry"))
                hint = getattr(e, "retry_after", None)
                wait_s = backoff if hint is None \
                    else min(max(hint, backoff), 30.0)
                if self._stop.wait(wait_s):
                    break
                backoff = min(backoff * 2, 30.0)
                continue
            # a wait timeout returns the advanced GLOBAL index, so index
            # motion alone is not a change — the result must differ
            changed = index is None or result != last
            index = new_index
            if new_index <= 0:
                # nothing to block on server-side (nonexistent key):
                # pace the poll instead of hot-looping
                self._stop.wait(min(_parse_wait_s(self.wait), 1.0))
            if changed:
                last = result
                handler(new_index, result)
                delivered += 1
                if max_events is not None and delivered >= max_events:
                    return delivered
        return delivered


# ------------------------------------------------------------ type funcs

def _key(client, index, wait, p) -> Tuple[Any, int]:
    row, idx = client.kv_get(p["key"], index=index, wait=wait)
    if row is None:
        return None, idx
    value = row.get("Value")
    # empty value decodes to "" — only a MISSING row maps to None
    return {"Key": p["key"],
            "Value": value.decode(errors="replace")
            if value is not None else ""}, idx


def _keyprefix(client, index, wait, p) -> Tuple[Any, int]:
    rows, idx = client.kv_list_blocking(p["prefix"], index=index,
                                        wait=wait)
    return ([{"Key": r["Key"],
              "Value": r["Value"].decode(errors="replace")
              if r.get("Value") is not None else ""}
             for r in rows], idx)


def _services(client, index, wait, p) -> Tuple[Any, int]:
    out, idx, _ = client._call("GET", "/v1/catalog/services",
                               {"index": index, "wait": wait})
    return out, idx


def _nodes(client, index, wait, p) -> Tuple[Any, int]:
    out, idx, _ = client._call("GET", "/v1/catalog/nodes",
                               {"index": index, "wait": wait})
    return out, idx


def _service(client, index, wait, p) -> Tuple[Any, int]:
    out, idx, _ = client._call(
        "GET", f"/v1/health/service/{p['service']}",
        {"index": index, "wait": wait,
         "tag": p.get("tag"),
         "passing": "" if p.get("passing") else None})
    return out, idx


def _checks(client, index, wait, p) -> Tuple[Any, int]:
    state = p.get("state", "any")
    out, idx, _ = client._call("GET", f"/v1/health/state/{state}",
                               {"index": index, "wait": wait})
    return out, idx


def _event(client, index, wait, p) -> Tuple[Any, int]:
    # user events carry no blocking index in the oracle ring: poll and
    # synthesize an index from the newest event id (watch.go's event
    # watch also tracks its own high-water mark)
    import time as _time
    out, _idx, _ = client._call("GET", "/v1/event/list",
                                {"name": p.get("name")})
    top = max((int(e["ID"]) for e in out), default=0)
    if index is not None and top <= index:
        _time.sleep(min(_parse_wait_s(wait), 1.0))
    return out, top


def _connect_roots(client, index, wait, p) -> Tuple[Any, int]:
    # CA root watch (funcs.go connectRootsWatch): fires on rotation —
    # the ActiveRootID flips to the new root
    out, idx, _ = client._call("GET", "/v1/connect/ca/roots",
                               {"index": index, "wait": wait})
    return out, idx


def _connect_leaf(client, index, wait, p) -> Tuple[Any, int]:
    # leaf-cert watch (funcs.go connectLeafWatch): fires when the
    # agent re-issues the service's leaf (rotation, expiry)
    out, idx, _ = client._call(
        "GET", f"/v1/agent/connect/ca/leaf/{p['service']}",
        {"index": index, "wait": wait})
    if isinstance(out, dict):
        # strip volatile validity stamps so a re-issued-but-identical
        # cert doesn't fire spuriously while a real rotation does
        out = {k: v for k, v in out.items()
               if k in ("SerialNumber", "CertPEM", "Service")}
    return out, idx


def _agent_service(client, index, wait, p) -> Tuple[Any, int]:
    # local service watch (funcs.go agentServiceWatch): hash-based in
    # the reference; here the local-state poll cycle paces the loop
    out, idx, _ = client._call(
        "GET", f"/v1/agent/service/{p['service_id']}",
        {"index": index, "wait": wait})
    return out, idx


def _parse_wait_s(wait: str) -> float:
    import re
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", wait)
    if not m:
        return 1.0
    scale = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}
    return float(m.group(1)) * scale[m.group(2) or "s"]


# per-type required parameters (Parse-time validation, watch.go:21)
REQUIRED_PARAMS: Dict[str, tuple] = {
    "key": ("key",), "keyprefix": ("prefix",), "service": ("service",),
    "services": (), "nodes": (), "checks": (), "event": (),
    "connect_roots": (), "connect_leaf": ("service",),
    "agent_service": ("service_id",),
}

WATCH_FUNCS: Dict[str, Callable] = {
    "key": _key,
    "keyprefix": _keyprefix,
    "services": _services,
    "nodes": _nodes,
    "service": _service,
    "checks": _checks,
    "event": _event,
    "connect_roots": _connect_roots,
    "connect_leaf": _connect_leaf,
    "agent_service": _agent_service,
}
