"""Python client library — the `api/` package equivalent (reference
api/api.go: full Go client over HTTP; Lock/Semaphore in api/lock.go,
api/semaphore.go).  Pure stdlib (urllib) so it has no dependency on the
framework internals, mirroring how the reference keeps `api/` an
independent module."""

from __future__ import annotations

import base64
import http.client
import json
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


class ApiError(Exception):
    """HTTP-level error (the server answered with a status >= 400).
    `ambiguous` says whether the request MAY have taken effect anyway —
    the distinction a history collector needs to classify outcomes
    (Jepsen's :ok / :fail / :info trichotomy).  `nack` marks the
    server's explicit definitely-NOT-applied rejections (rate limit,
    apply admission) — for a write, a nack is a proof of
    non-commitment, unlike a generic 500 that may have fired after the
    entry was proposed.  `reason` carries the machine-readable
    X-Consul-Reason header when the server stamped one."""

    ambiguous = False
    nack = False

    def __init__(self, code: int, body: str):
        super().__init__(f"HTTP {code}: {body}")
        self.code = code
        self.body = body
        self.reason: Optional[str] = None
        self.retry_after: Optional[float] = None


class ApiRateLimitError(ApiError):
    """429 + Retry-After from the ingress rate limiter: the request
    was shed BEFORE any store or raft work, so a rejected write cannot
    have committed (ambiguous=False, nack=True).  `retry_after` is the
    server's hint in seconds; the retrying helpers honor it with
    capped jittered backoff (retry_backoff)."""

    nack = True

    def __init__(self, code: int, body: str,
                 retry_after: Optional[float] = None):
        super().__init__(code, body)
        self.reason = "rate-limited"
        self.retry_after = retry_after


class ApiOverloadError(ApiError):
    """503 + X-Consul-Reason queue-full/deadline: the leader's apply
    admission NACKed the write strictly before the raft append — it
    was never proposed and definitely did not commit (nack=True).
    The unambiguous face of leader overload (vs the timeout it
    replaces)."""

    nack = True

    def __init__(self, code: int, body: str, reason: str):
        super().__init__(code, body)
        self.reason = reason


class ApiTimeoutError(ApiError):
    """The request was (possibly) sent but no answer arrived in time —
    a socket timeout, reset, or broken pipe.  AMBIGUOUS: a write may
    have committed before the answer was lost; callers recording
    client histories must treat the outcome as unknown, not failed."""

    ambiguous = True

    def __init__(self, detail: str):
        Exception.__init__(self, f"timeout/ambiguous: {detail}")
        self.code = None
        self.body = detail


class ApiConnectionError(ApiError):
    """No listener reachable (connection refused / no such host): the
    request never entered a server, so a write DEFINITELY did not
    take effect.  Safe to count as a failure in a client history."""

    ambiguous = False

    def __init__(self, detail: str):
        Exception.__init__(self, f"connection failed: {detail}")
        self.code = None
        self.body = detail


# reasons that prove the request never reached a serving process (the
# TCP connect itself was rejected) vs. everything else, where bytes may
# already have crossed into a server before the failure
_DEFINITE_REASONS = (ConnectionRefusedError, socket.gaierror)


def _classify_oserror(e: BaseException, url: str) -> ApiError:
    if isinstance(e, _DEFINITE_REASONS):
        return ApiConnectionError(f"{url}: {e}")
    return ApiTimeoutError(f"{url}: {e}")


# X-Consul-Reason values that mark an explicit server-side NACK of a
# write before it could reach the raft log
_NACK_REASONS = ("queue-full", "deadline")


def _classify_http_error(e) -> ApiError:
    """HTTPError → the typed taxonomy, discriminating on status +
    X-Consul-Reason (ISSUE 13).  A 429 counts as rate limiting only
    when the limiter's fingerprints (Retry-After or the reason header)
    are present — /v1/agent/health also answers 429 for 'warning' and
    must stay a plain ApiError."""
    body = e.read().decode(errors="replace")
    reason = e.headers.get("X-Consul-Reason")
    ra = e.headers.get("Retry-After")
    if e.code == 429 and (ra is not None or reason == "rate-limited"):
        try:
            retry_after = float(ra) if ra is not None else None
        except ValueError:
            retry_after = None
        return ApiRateLimitError(e.code, body, retry_after=retry_after)
    if e.code == 503 and reason in _NACK_REASONS:
        return ApiOverloadError(e.code, body, reason)
    err = ApiError(e.code, body)
    err.reason = reason
    return err


def retry_backoff(e: Optional[BaseException] = None, attempt: int = 0,
                  base: float = 0.2, cap: float = 5.0) -> float:
    """Seconds to sleep before retrying after `e`: the server's
    Retry-After hint when it sent one (429), else exponential in
    `attempt` — either way capped at `cap` and jittered to half-full
    so a thundering herd of limited clients decorrelates."""
    import random
    hint = getattr(e, "retry_after", None)
    d = hint if hint is not None else base * (2 ** attempt)
    return min(cap, max(0.0, d)) * (0.5 + random.random() * 0.5)


def consistency_params(stale: bool = False,
                       max_stale: Optional[str] = None,
                       consistent: bool = False) -> dict:
    """Query params for the read plane's consistency modes (the
    reference's QueryOptions AllowStale / MaxStaleDuration /
    RequireConsistent).  `max_stale` implies stale."""
    return {"stale": "" if (stale or max_stale) else None,
            "max_stale": max_stale,
            "consistent": "" if consistent else None}


class Client:
    def __init__(self, address: str = "http://127.0.0.1:8500",
                 token: Optional[str] = None,
                 timeout: float = 330.0):
        self.address = address.rstrip("/")
        self.token = token
        self.timeout = timeout
        # consistency metadata of the LAST response (X-Consul-
        # KnownLeader / X-Consul-LastContact) — how stale the data the
        # server handed back may be (api.QueryMeta role)
        self.last_known_leader: Optional[bool] = None
        self.last_contact_ms: Optional[int] = None

    # ------------------------------------------------------------- transport

    def _call(self, verb: str, path: str, params: Dict[str, Any] | None = None,
              body: bytes | None = None,
              timeout: Optional[float] = None) -> Tuple[Any, int, bytes]:
        qs = urllib.parse.urlencode(
            {k: v for k, v in (params or {}).items() if v is not None})
        url = f"{self.address}{path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=body, method=verb)
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout if timeout is not None
                    else self.timeout) as resp:
                raw = resp.read()
                idx = int(resp.headers.get("X-Consul-Index") or 0)
                kl = resp.headers.get("X-Consul-KnownLeader")
                if kl is not None:
                    self.last_known_leader = kl == "true"
                lc = resp.headers.get("X-Consul-LastContact")
                if lc is not None:
                    self.last_contact_ms = int(lc)
                ctype = resp.headers.get("Content-Type", "")
                if "json" in ctype:
                    return (json.loads(raw) if raw else None), idx, raw
                return None, idx, raw
        except urllib.error.HTTPError as e:
            raise _classify_http_error(e) from None
        except urllib.error.URLError as e:
            # connect-phase failures ride URLError; split DEFINITE
            # (refused: no listener, the write cannot have applied)
            # from AMBIGUOUS (timeout/reset: it may have committed)
            reason = e.reason if isinstance(e.reason, BaseException) \
                else OSError(str(e.reason))
            raise _classify_oserror(reason, url) from None
        except (TimeoutError, socket.timeout) as e:
            # read-phase timeouts surface raw from http.client
            raise ApiTimeoutError(f"{url}: {e}") from None
        except (ConnectionError, OSError) as e:
            raise _classify_oserror(e, url) from None
        except http.client.HTTPException as e:
            # torn response (peer died mid-reply): request was sent,
            # outcome unknown
            raise ApiTimeoutError(f"{url}: {e}") from None

    # -------------------------------------------------------------------- kv

    def kv_put(self, key: str, value: bytes | str, flags: int = 0,
               cas: Optional[int] = None, acquire: Optional[str] = None,
               release: Optional[str] = None) -> bool:
        if isinstance(value, str):
            value = value.encode()
        params = {"flags": flags or None, "cas": cas,
                  "acquire": acquire, "release": release}
        out, _, _ = self._call("PUT", f"/v1/kv/{key}", params, value)
        return bool(out)

    def kv_get(self, key: str, index: Optional[int] = None,
               wait: Optional[str] = None,
               consistent: bool = False, stale: bool = False,
               max_stale: Optional[str] = None
               ) -> Tuple[Optional[dict], int]:
        try:
            out, idx, _ = self._call(
                "GET", f"/v1/kv/{key}",
                {"index": index, "wait": wait,
                 **consistency_params(stale, max_stale, consistent)})
        except ApiError as e:
            if e.code == 404:
                return None, 0
            raise
        row = out[0]
        row["Value"] = base64.b64decode(row["Value"]) if row["Value"] else b""
        return row, idx

    def kv_list(self, prefix: str, stale: bool = False,
                max_stale: Optional[str] = None) -> List[dict]:
        return self.kv_list_blocking(prefix, stale=stale,
                                     max_stale=max_stale)[0]

    def kv_list_blocking(self, prefix: str, index: Optional[int] = None,
                         wait: Optional[str] = None, stale: bool = False,
                         max_stale: Optional[str] = None):
        """Recurse read returning (rows, index) — the watch-loop shape
        (one return type; kv_list is the rows-only convenience)."""
        try:
            out, idx, _ = self._call(
                "GET", f"/v1/kv/{prefix}",
                {"recurse": "", "index": index, "wait": wait,
                 **consistency_params(stale, max_stale)})
        except ApiError as e:
            if e.code == 404:
                return [], 0
            raise
        for row in out:
            row["Value"] = base64.b64decode(row["Value"]) if row["Value"] else b""
        return out, idx

    def kv_keys(self, prefix: str, separator: str = "") -> List[str]:
        try:
            out, _, _ = self._call("GET", f"/v1/kv/{prefix}",
                                   {"keys": "", "separator": separator or None})
            return out
        except ApiError as e:
            if e.code == 404:
                return []
            raise

    def kv_delete(self, key: str, recurse: bool = False) -> bool:
        out, _, _ = self._call("DELETE", f"/v1/kv/{key}",
                               {"recurse": ""} if recurse else {})
        return bool(out)

    # --------------------------------------------------------------- catalog

    def catalog_nodes(self, near: Optional[str] = None,
                      filter: Optional[str] = None, stale: bool = False,
                      max_stale: Optional[str] = None) -> List[dict]:
        return self._call(
            "GET", "/v1/catalog/nodes",
            {"near": near, "filter": filter,
             **consistency_params(stale, max_stale)})[0]

    def catalog_services(self) -> Dict[str, List[str]]:
        return self._call("GET", "/v1/catalog/services")[0]

    def catalog_service(self, name: str, tag: Optional[str] = None,
                        near: Optional[str] = None,
                        filter: Optional[str] = None,
                        stale: bool = False,
                        max_stale: Optional[str] = None) -> List[dict]:
        return self._call(
            "GET", f"/v1/catalog/service/{name}",
            {"tag": tag, "near": near, "filter": filter,
             **consistency_params(stale, max_stale)})[0]

    def catalog_register(self, node: str, address: str,
                         service: Optional[dict] = None,
                         check: Optional[dict] = None) -> bool:
        body = {"Node": node, "Address": address}
        if service:
            body["Service"] = service
        if check:
            body["Check"] = check
        return self._call("PUT", "/v1/catalog/register", None,
                          json.dumps(body).encode())[0]

    def catalog_deregister(self, node: str,
                           service_id: Optional[str] = None) -> bool:
        body = {"Node": node}
        if service_id:
            body["ServiceID"] = service_id
        return self._call("PUT", "/v1/catalog/deregister", None,
                          json.dumps(body).encode())[0]

    # ---------------------------------------------------------------- health

    def health_service(self, name: str, passing: bool = False,
                       tag: Optional[str] = None,
                       near: Optional[str] = None,
                       index: Optional[int] = None,
                       wait: Optional[str] = None,
                       filter: Optional[str] = None,
                       stale: bool = False,
                       max_stale: Optional[str] = None
                       ) -> Tuple[List[dict], int]:
        params = {"tag": tag, "near": near, "index": index, "wait": wait,
                  "filter": filter,
                  **consistency_params(stale, max_stale)}
        if passing:
            params["passing"] = ""
        out, idx, _ = self._call("GET", f"/v1/health/service/{name}", params)
        return out, idx

    def health_state(self, state: str = "any") -> List[dict]:
        return self._call("GET", f"/v1/health/state/{state}")[0]

    # ----------------------------------------------------------------- agent

    def agent_self(self) -> dict:
        return self._call("GET", "/v1/agent/self")[0]

    def agent_members(self, segment: Optional[str] = None) -> List[dict]:
        return self._call("GET", "/v1/agent/members",
                          {"segment": segment})[0]

    def agent_events(self, since: int = 0, wait: Optional[str] = None,
                     name: Optional[str] = None,
                     limit: Optional[int] = None) -> tuple:
        """Flight-recorder journal read: (events, last_seq).  `since`
        is the seq cursor; with `wait` the call blocks server-side
        until a newer event lands (blocking-query shape)."""
        params = {"since": str(since)}
        if wait is not None:
            params["wait"] = wait
        if name is not None:
            params["name"] = name
        if limit is not None:
            params["limit"] = str(limit)
        out, idx, _ = self._call("GET", "/v1/agent/events", params)
        return out, idx

    def agent_traces(self, since: int = 0,
                     trace_id: Optional[str] = None,
                     limit: Optional[int] = None) -> tuple:
        """Trace-span ring read: (spans, cursor).  `since` is the span
        seq cursor (spans with seq > since), `trace_id` filters to one
        correlated trace — the pair the WAN probe and federation view
        use to correlate cross-DC spans without re-downloading the
        ring each poll."""
        params: Dict[str, Any] = {"since": str(since) if since else None,
                                  "trace_id": trace_id}
        if limit is not None:
            params["limit"] = str(limit)
        out, idx, _ = self._call("GET", "/v1/agent/traces", params)
        return out, idx

    def agent_profile(self) -> dict:
        """The always-on tick profiler's EMA table + recompile count."""
        return self._call("GET", "/v1/agent/profile")[0]

    def internal_xds(self, local: bool = False) -> dict:
        """The mesh-control-plane table (/v1/internal/ui/xds, ISSUE
        16): with `local` this node's OWN per-proxy rows
        ({node, proxies}); without it the merged configured-fleet view
        ({nodes, proxies}) — 404 (ApiError) when no fleet map is
        configured on the serving node."""
        params = {"local": "1"} if local else None
        return self._call("GET", "/v1/internal/ui/xds", params)[0]

    def agent_service_register(self, name: str, service_id: Optional[str] = None,
                               port: int = 0, tags: List[str] | None = None,
                               check: Optional[dict] = None) -> None:
        body = {"Name": name, "ID": service_id or name, "Port": port,
                "Tags": tags or []}
        if check:
            body["Check"] = check
        self._call("PUT", "/v1/agent/service/register", None,
                   json.dumps(body).encode())

    def agent_service_deregister(self, service_id: str) -> None:
        self._call("PUT", f"/v1/agent/service/deregister/{service_id}")

    def agent_check_register(self, name: str, check_id: Optional[str] = None,
                             service_id: str = "") -> None:
        self._call("PUT", "/v1/agent/check/register", None, json.dumps(
            {"Name": name, "CheckID": check_id or name,
             "ServiceID": service_id}).encode())

    def agent_check_update(self, check_id: str, status: str,
                           note: str = "") -> None:
        verb = {"passing": "pass", "warning": "warn",
                "critical": "fail"}[status]
        self._call("PUT", f"/v1/agent/check/{verb}/{check_id}",
                   {"note": note or None})

    def agent_force_leave(self, node: str) -> None:
        self._call("PUT", f"/v1/agent/force-leave/{node}")

    def agent_maintenance(self, enable: bool, reason: str = "") -> None:
        self._call("PUT", "/v1/agent/maintenance",
                   {"enable": "true" if enable else "false",
                    "reason": reason or None})

    def agent_service_maintenance(self, service_id: str, enable: bool,
                                  reason: str = "") -> None:
        self._call("PUT", f"/v1/agent/service/maintenance/{service_id}",
                   {"enable": "true" if enable else "false",
                    "reason": reason or None})

    def agent_token_update(self, slot: str, token_value: str) -> None:
        self._call("PUT", f"/v1/agent/token/{slot}", None,
                   json.dumps({"Token": token_value}).encode())

    def agent_join(self, address: str) -> None:
        self._call("PUT", f"/v1/agent/join/{address}")

    def agent_host(self) -> dict:
        return self._call("GET", "/v1/agent/host")[0]

    def agent_health_service_by_id(self, service_id: str) -> dict:
        # 429 (warning) / 503 (critical, maintenance) still carry the
        # aggregated JSON body (agent_endpoint.go AgentHealthServiceByID)
        try:
            return self._call(
                "GET", f"/v1/agent/health/service/id/{service_id}")[0]
        except ApiError as e:
            if e.code in (429, 503):
                return json.loads(e.body)
            raise

    def agent_health_service_by_name(self, name: str) -> List[dict]:
        try:
            return self._call(
                "GET", f"/v1/agent/health/service/name/{name}")[0]
        except ApiError as e:
            if e.code in (429, 503):
                return json.loads(e.body)
            raise

    def catalog_datacenters(self) -> List[str]:
        return self._call("GET", "/v1/catalog/datacenters")[0]

    # -------------------------------------------------------------- sessions

    def session_create(self, node: Optional[str] = None, ttl: str = "",
                       behavior: str = "release") -> str:
        body: Dict[str, Any] = {"Behavior": behavior}
        if node:
            body["Node"] = node
        if ttl:
            body["TTL"] = ttl
        out, _, _ = self._call("PUT", "/v1/session/create", None,
                               json.dumps(body).encode())
        return out["ID"]

    def session_destroy(self, sid: str) -> bool:
        return self._call("PUT", f"/v1/session/destroy/{sid}")[0]

    def session_renew(self, sid: str) -> dict:
        return self._call("PUT", f"/v1/session/renew/{sid}")[0][0]

    def session_list(self) -> List[dict]:
        return self._call("GET", "/v1/session/list")[0]

    # --------------------------------------------------------- coordinates

    def coordinate_nodes(self) -> List[dict]:
        return self._call("GET", "/v1/coordinate/nodes")[0]

    def coordinate_node(self, node: str) -> List[dict]:
        return self._call("GET", f"/v1/coordinate/node/{node}")[0]

    def coordinate_update(self, node: str, coord: dict) -> bool:
        return self._call("PUT", "/v1/coordinate/update", None,
                          json.dumps({"Node": node,
                                      "Coord": coord}).encode())[0]

    def coordinate_datacenters(self) -> List[dict]:
        return self._call("GET", "/v1/coordinate/datacenters")[0]

    # --------------------------------------------------------------- events

    def event_fire(self, name: str, payload: bytes | str = b"") -> dict:
        if isinstance(payload, str):
            payload = payload.encode()
        return self._call("PUT", f"/v1/event/fire/{name}", None, payload)[0]

    def event_list(self, name: Optional[str] = None) -> List[dict]:
        return self._call("GET", "/v1/event/list", {"name": name})[0]

    # ------------------------------------------------------------------ txn

    def txn(self, ops: List[dict]) -> dict:
        try:
            return self._call("PUT", "/v1/txn", None,
                              json.dumps(ops).encode())[0]
        except ApiError as e:
            if e.code == 409:   # rolled back — body carries the op errors
                return json.loads(str(e).split(": ", 1)[1])
            raise

    # ------------------------------------------------------------- snapshot

    def snapshot_save(self) -> bytes:
        return self._call("GET", "/v1/snapshot")[2]

    def snapshot_restore(self, snap: bytes) -> None:
        self._call("PUT", "/v1/snapshot", None, snap)

    # ----------------------------------------------------------------- lock

    def lock_acquire(self, key: str, value: bytes = b"", ttl: str = "15s",
                     retries: int = 30, retry_wait: float = 0.2) -> Optional[str]:
        """api/lock.go Lock(): session + acquire loop.  A rate-limited
        attempt (429) costs a retry slot and backs off per the
        server's Retry-After hint (capped, jittered) instead of
        hammering a limiter that just shed us."""
        sid = self.session_create(ttl=ttl)
        for attempt in range(retries):
            try:
                if self.kv_put(key, value, acquire=sid):
                    return sid
            except ApiRateLimitError as e:
                time.sleep(retry_backoff(e, attempt, base=retry_wait))
                continue
            time.sleep(retry_wait)
        self.session_destroy(sid)
        return None

    def lock_release(self, key: str, sid: str) -> bool:
        ok = self.kv_put(key, b"", release=sid)
        self.session_destroy(sid)
        return ok

    # ------------------------------------------------------------------ acl

    def acl_bootstrap(self) -> dict:
        return self._call("PUT", "/v1/acl/bootstrap")[0]

    def acl_policy_create(self, name: str, rules: str,
                          description: str = "") -> dict:
        return self._call("PUT", "/v1/acl/policy", None, json.dumps(
            {"Name": name, "Rules": rules,
             "Description": description}).encode())[0]

    def acl_policy_read(self, pid: str) -> dict:
        return self._call("GET", f"/v1/acl/policy/{pid}")[0]

    def acl_policy_list(self) -> List[dict]:
        return self._call("GET", "/v1/acl/policies")[0]

    def acl_policy_delete(self, pid: str) -> bool:
        return bool(self._call("DELETE", f"/v1/acl/policy/{pid}")[0])

    def acl_token_create(self, policies: List[str] | None = None,
                         description: str = "",
                         service_identities: List[dict] | None = None,
                         node_identities: List[dict] | None = None) -> dict:
        body = {"Policies": [{"Name": p} for p in (policies or [])],
                "Description": description}
        if service_identities:
            body["ServiceIdentities"] = service_identities
        if node_identities:
            body["NodeIdentities"] = node_identities
        return self._call("PUT", "/v1/acl/token", None,
                          json.dumps(body).encode())[0]

    def acl_token_read(self, accessor: str) -> dict:
        return self._call("GET", f"/v1/acl/token/{accessor}")[0]

    def acl_token_self(self) -> dict:
        return self._call("GET", "/v1/acl/token/self")[0]

    def acl_token_list(self) -> List[dict]:
        return self._call("GET", "/v1/acl/tokens")[0]

    def acl_token_delete(self, accessor: str) -> bool:
        return bool(self._call("DELETE", f"/v1/acl/token/{accessor}")[0])

    def acl_token_clone(self, accessor: str) -> dict:
        return self._call("PUT", f"/v1/acl/token/{accessor}/clone")[0]

    # ------------------------------------------------------- prepared queries
    # (api/prepared_query.go PreparedQuery client)

    def query_create(self, definition: dict) -> str:
        out, _, _ = self._call("POST", "/v1/query", None,
                               json.dumps(definition).encode())
        return out["ID"]

    def query_list(self) -> List[dict]:
        return self._call("GET", "/v1/query")[0]

    def query_get(self, qid: str) -> Optional[dict]:
        try:
            out = self._call("GET", f"/v1/query/{qid}")[0]
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        return out[0] if out else None

    def query_update(self, qid: str, definition: dict) -> bool:
        return bool(self._call("PUT", f"/v1/query/{qid}", None,
                               json.dumps(definition).encode())[0])

    def query_delete(self, qid: str) -> bool:
        return bool(self._call("DELETE", f"/v1/query/{qid}")[0])

    def query_execute(self, name_or_id: str, limit: int = 0,
                      near: Optional[str] = None) -> Optional[dict]:
        try:
            return self._call(
                "GET", f"/v1/query/{name_or_id}/execute",
                {"limit": limit or None, "near": near})[0]
        except ApiError as e:
            if e.code == 404:
                return None
            raise

    def query_explain(self, name: str) -> Optional[dict]:
        try:
            return self._call("GET", f"/v1/query/{name}/explain")[0]
        except ApiError as e:
            if e.code == 404:
                return None
            raise

    # --------------------------------------------------------- config entries

    def config_write(self, entry: dict) -> bool:
        """PUT /v1/config (api/config_entry.go ConfigEntries.Set)."""
        return bool(self._call("PUT", "/v1/config", None,
                               json.dumps(entry).encode())[0])

    def config_read(self, kind: str, name: str) -> dict:
        return self._call("GET", f"/v1/config/{kind}/{name}")[0]

    def config_list(self, kind: str) -> List[dict]:
        return self._call("GET", f"/v1/config/{kind}")[0]

    def config_delete(self, kind: str, name: str) -> bool:
        return bool(self._call("DELETE",
                               f"/v1/config/{kind}/{name}")[0])

    # -------------------------------------------------------------- intentions

    def intention_create(self, source: str, destination: str,
                         action: str = "allow",
                         description: str = "") -> str:
        out = self._call("PUT", "/v1/connect/intentions", None,
                         json.dumps({"SourceName": source,
                                     "DestinationName": destination,
                                     "Action": action,
                                     "Description": description}).encode())
        return out[0]["ID"]

    def intention_list(self) -> List[dict]:
        return self._call("GET", "/v1/connect/intentions")[0]

    def intention_delete(self, iid: str) -> bool:
        return bool(self._call("DELETE",
                               f"/v1/connect/intentions/{iid}")[0])

    def intention_check(self, source: str, destination: str) -> bool:
        out = self._call("GET", "/v1/connect/intentions/check",
                         {"source": source, "destination": destination})
        return bool(out[0].get("Allowed"))

    def intention_match(self, by: str, name: str) -> dict:
        return self._call("GET", "/v1/connect/intentions/match",
                          {"by": by, "name": name})[0]

    # -------------------------------------------------------------- connect ca

    def connect_ca_roots(self) -> dict:
        return self._call("GET", "/v1/connect/ca/roots")[0]

    def connect_ca_leaf(self, service: str) -> dict:
        """GET /v1/agent/connect/ca/leaf/<service> — the agent-cached
        CA-issued leaf for a service (agent_endpoint.go leaf cert)."""
        return self._call("GET",
                          f"/v1/agent/connect/ca/leaf/{service}")[0]

    def connect_authorize(self, target: str,
                          client_cert_uri: str) -> dict:
        """PUT /v1/agent/connect/authorize (agent_endpoint.go
        ConnectAuthorize): may this client URI reach `target`?"""
        return self._call(
            "PUT", "/v1/agent/connect/authorize", None,
            json.dumps({"Target": target,
                        "ClientCertURI": client_cert_uri}).encode())[0]

    def health_connect(self, name: str) -> list:
        """GET /v1/health/connect/<name> — mesh-reachable (proxy)
        endpoints for a service."""
        return self._call("GET", f"/v1/health/connect/{name}")[0]

    def connect_ca_rotate(self) -> dict:
        return self._call("PUT", "/v1/connect/ca/rotate")[0]

    def connect_ca_config(self) -> dict:
        return self._call("GET", "/v1/connect/ca/configuration")[0]

    def connect_ca_set_config(self, config: dict) -> bool:
        return bool(self._call("PUT", "/v1/connect/ca/configuration",
                               None, json.dumps(config).encode())[0])

    # ------------------------------------------------------------ login/logout

    def acl_login(self, auth_method: str, bearer_token: str,
                  meta: Optional[dict] = None) -> dict:
        """PUT /v1/acl/login → the minted token (acl_endpoint.go
        Login)."""
        return self._call("PUT", "/v1/acl/login", None, json.dumps(
            {"AuthMethod": auth_method, "BearerToken": bearer_token,
             "Meta": meta or {}}).encode())[0]

    def acl_logout(self) -> bool:
        """PUT /v1/acl/logout under this client's token."""
        return bool(self._call("PUT", "/v1/acl/logout")[0])
