"""Fast HTTP front for the agent API: hot KV ops on a minimal parser.

The round-3 KV numbers (2.9k PUT/s, 3.6k GET/s on this rig's single
core) were bounded by http.server's per-request machinery — measured
ceiling for a BaseHTTPRequestHandler echo on this box is ~5.2k req/s,
below the reference's absolute GET bar (7,524.9 req/s,
bench/results-0.7.1.md:63-72).  A raw per-connection recv/sendall loop
measures ~10.8k req/s on the same core, so the server core — not the
store — was the bottleneck.

This module is that raw loop, made safe: each connection gets a
thread; simple KV GET/PUT/DELETE (no blocking/recurse/keys/filter/
cross-dc/cached semantics) are parsed and answered inline against the
store with the exact response shapes of the legacy handler; EVERYTHING
else — the other ~100 routes, blocking queries, ?recurse, txn — is
replayed byte-for-byte through the existing BaseHTTPRequestHandler
subclass over an in-memory request file, so the full surface keeps one
implementation and the hot path cannot drift from it semantically
(both call the same store methods and the same authorizer).

The reference's equivalent is Go's net/http serving mux — one server
core fast enough for every route; Python needs the split to clear the
same bar on one core.
"""

from __future__ import annotations

import io
import json
import socket
import threading
import urllib.parse
from typing import Optional
from consul_tpu.utils.net import shutdown_and_close

# query params that force the legacy path for /v1/kv (blocking reads,
# recursion, listings, cross-dc, filtered or cached semantics).
# ?stale / ?max_stale deliberately ABSENT: the stale follower read is
# the read plane's hot path (readplane.py) and is served inline below;
# only a violated max_stale bound falls back so the legacy handler
# shapes the 500 + rejected counter + flight event.
_KV_COLD_PARAMS = frozenset((
    "recurse", "keys", "index", "wait", "consistent", "dc",
    "filter", "cached", "separator", "raw", "near",
))

_HOP = b"HTTP/1.1 "

# hoisted hot-path telemetry keys (one tuple/dict per PROCESS, not per
# request — the readplane mode counter rides every hot GET)
_RP_STALE = ("readplane", "stale")
_RP_DEFAULT = ("readplane", "default")
_RP_KV_LABELS = {"route": "kv"}


class _FakeSock:
    """Socket stand-in handed to the legacy handler for fallback
    requests: reads come from the captured request bytes, writes go to
    the real connection.  Framing cannot desync because the handler
    sees EXACTLY one request's bytes."""

    __slots__ = ("_data", "_conn")

    def __init__(self, data: bytes, conn: socket.socket):
        self._data = data
        self._conn = conn

    def makefile(self, mode: str, *a, **kw):
        if "r" in mode:
            return io.BytesIO(self._data)
        raise AssertionError("write side uses sendall")

    def sendall(self, data: bytes) -> None:
        self._conn.sendall(data)

    def setsockopt(self, *a) -> None:  # NODELAY already set on _conn
        pass


class FastKVServer:
    """Drop-in for ThreadingHTTPServer in ApiServer: same
    serve_forever/shutdown/server_close/server_address surface."""

    daemon_threads = True
    _HEAD_CAP = 65536                   # http.server's request cap
    _BODY_CAP = 64 * 1024 * 1024        # sanity bound; per-route caps
    #                                     (kv 512KB, txn) are stricter

    def __init__(self, addr, handler_cls, api_server):
        self._handler_cls = handler_cls
        self._api = api_server
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(256)
        self.server_address = self._sock.getsockname()
        self._running = False
        self._shutdown_done = threading.Event()
        # pre-set: shutdown() must not block 5s when serve_forever was
        # never started (only its finally would otherwise set this)
        self._shutdown_done.set()
        # (key, modify_index, has_session) -> serialized GET payload;
        # benign races (GIL dict ops), cleared wholesale past 4096 rows
        self._row_cache: dict = {}

    # ------------------------------------------------------ server surface

    def serve_forever(self) -> None:
        self._running = True
        self._shutdown_done.clear()
        try:
            while self._running:
                try:
                    conn, addr = self._sock.accept()
                except OSError:
                    break
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn, addr), daemon=True)
                t.start()
        finally:
            self._shutdown_done.set()

    def _close_listener(self) -> None:
        shutdown_and_close(self._sock)

    def shutdown(self) -> None:
        self._running = False
        self._close_listener()
        self._shutdown_done.wait(5.0)

    def server_close(self) -> None:
        self._close_listener()

    # --------------------------------------------------------- connection

    _IDLE_TIMEOUT = 300.0   # reap abandoned keep-alive connections:
    #                         a parked thread per dead client would
    #                         accumulate across a long-lived agent

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self._IDLE_TIMEOUT)
            buf = b""
            while True:
                # read one request head (bounded: http.server caps the
                # head at 64KB; garbage with no CRLFCRLF — e.g. a TLS
                # hello at the plaintext port — must not buffer forever)
                while b"\r\n\r\n" not in buf:
                    if len(buf) > self._HEAD_CAP:
                        conn.sendall(
                            b"HTTP/1.1 431 Request Header Fields Too "
                            b"Large\r\nContent-Length: 0\r\n\r\n")
                        return
                    try:
                        chunk = conn.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                head_end = buf.index(b"\r\n\r\n") + 4
                head = buf[:head_end]
                # parse request line + the few headers the hot path and
                # framing need
                line_end = head.index(b"\r\n")
                try:
                    verb, target, version = \
                        head[:line_end].decode("latin-1").split(" ", 2)
                except ValueError:
                    conn.sendall(b"HTTP/1.1 400 Bad Request\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                    return
                clen = None
                token = None
                trace_id = None
                expect_100 = False
                want_close = version == "HTTP/1.0"
                for hline in head[line_end + 2:-4].split(b"\r\n"):
                    k, _, v = hline.partition(b":")
                    kl = k.lower()
                    if kl == b"content-length":
                        # strict digits only: int() also accepts
                        # "+4"/"4_2", which a stricter front proxy
                        # would frame differently (smuggling vector)
                        sv = v.strip()
                        this_len = int(sv) if sv.isdigit() else -1
                        if this_len < 0 or (clen is not None
                                            and clen != this_len):
                            # malformed or conflicting duplicates:
                            # framing could desync on keep-alive
                            conn.sendall(
                                b"HTTP/1.1 400 Bad Request\r\n"
                                b"Content-Length: 0\r\n\r\n")
                            return
                        clen = this_len
                    elif kl == b"transfer-encoding":
                        # chunked bodies would be re-parsed as the next
                        # request head; refuse rather than desync
                        conn.sendall(
                            b"HTTP/1.1 501 Not Implemented\r\n"
                            b"Content-Length: 0\r\n\r\n")
                        return
                    elif kl == b"x-consul-token":
                        token = v.strip().decode("latin-1")
                    elif kl == b"x-consul-trace-id":
                        # explicit tracing only on the hot path: an
                        # untraced KV op pays zero span overhead, a
                        # traced one records like the legacy front
                        trace_id = v.strip().decode("latin-1")
                    elif kl == b"authorization":
                        av = v.strip().decode("latin-1")
                        if token is None and av.startswith("Bearer "):
                            token = av[7:].strip()
                    elif kl == b"connection":
                        cv = v.strip().lower()
                        if cv == b"close":
                            want_close = True
                        elif cv == b"keep-alive":
                            want_close = False
                    elif kl == b"expect":
                        expect_100 = b"100-continue" in v.strip().lower()
                if clen is None:
                    clen = 0
                if clen > self._BODY_CAP:
                    # absurd Content-Length must not buffer before the
                    # per-route size checks can see it
                    conn.sendall(b"HTTP/1.1 413 Payload Too Large\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                    return
                if expect_100 and clen and len(buf) < head_end + clen:
                    # BaseHTTPRequestHandler answers this before
                    # reading the body; clients (curl >1KB PUTs) wait
                    # for it
                    conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
                # read the body
                while len(buf) < head_end + clen:
                    try:
                        chunk = conn.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                body = buf[head_end:head_end + clen]
                request_bytes = buf[:head_end + clen]
                buf = buf[head_end + clen:]
                if expect_100:
                    # the interim 100 was already sent; the replayed
                    # fallback handler must not send a second one
                    kept = [ln for ln in
                            request_bytes[:head_end - 4].split(b"\r\n")
                            if not ln.lower().startswith(b"expect:")]
                    request_bytes = b"\r\n".join(kept) + b"\r\n\r\n" \
                        + body

                handled = self._try_hot(conn, verb, target, token, body,
                                        trace_id=trace_id,
                                        client=addr[0] if addr else "")
                if not handled:
                    self._fallback(conn, addr, request_bytes)
                if want_close:
                    return
        except OSError:
            pass   # routine ungraceful disconnect (RST, LB probe)
        except Exception:
            # a dying connection loop must not kill the acceptor —
            # but a non-socket failure here is a real server bug and
            # must be countable (consul.http.fastfront_error)
            from consul_tpu import telemetry
            telemetry.incr_counter(("http", "fastfront_error"),
                                   labels={"kind": "conn"})
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ----------------------------------------------------------- fallback

    def _fallback(self, conn, addr, request_bytes: bytes) -> None:
        """Replay the request through the legacy handler (full route
        surface).  The handler writes its response straight to the
        connection and is then discarded; the keep-alive loop stays
        ours."""
        self._handler_cls(_FakeSock(request_bytes, conn), addr, self)

    # ----------------------------------------------------------- hot path

    def _try_hot(self, conn, verb: str, target: str,
                 token: Optional[str], body: bytes,
                 trace_id: Optional[str] = None,
                 client: str = "") -> bool:
        if not target.startswith("/v1/kv/"):
            return False
        srv = self._api
        path, _, qs = target.partition("?")
        q = dict(urllib.parse.parse_qsl(qs, keep_blank_values=True)) \
            if qs else {}
        if any(p in q for p in _KV_COLD_PARAMS):
            return False
        key = path[len("/v1/kv/"):]
        if "%" in key or "+" in key:
            key = urllib.parse.unquote(key)
        if verb not in ("GET", "PUT", "DELETE"):
            return False
        store = srv.store
        from consul_tpu import telemetry
        import time as _time
        # read-plane mode resolution for the hot GET (readplane.py):
        # ?stale serves this replica inline unless its lag violates
        # ?max_stale (legacy path shapes that 500); a default-mode GET
        # on a follower with a configured fleet map must leader-forward
        # — also legacy.  The discipline rule holds: nothing below
        # performs a leader RPC for a stale read.
        stale = "stale" in q or "max_stale" in q
        if verb == "GET":
            rp = srv.readplane
            if stale:
                if not rp.hot_stale_ok(q):
                    return False
                telemetry.incr_counter(_RP_STALE,
                                       labels=_RP_KV_LABELS)
            else:
                if not rp.hot_default_ok():
                    return False
                telemetry.incr_counter(_RP_DEFAULT,
                                       labels=_RP_KV_LABELS)
        # parse numeric params BEFORE counting/handling: malformed
        # values fall back so the legacy path shapes the 400 (and is
        # the only one to count the request)
        try:
            flags = int(q.get("flags", 0))
            cas = int(q["cas"]) if "cas" in q else None
        except ValueError:
            return False
        # ingress rate limiting on the hot path (ISSUE 13): the shed
        # must happen HERE, inline — falling back to the legacy front
        # to say "429" would make the shed path slower than the served
        # path, the opposite of load shedding.  Disabled mode costs
        # one attribute read.
        rl = srv.ratelimit
        if rl.mode != "disabled":
            wait = rl.check(token or client,
                            "read" if verb == "GET" else "write")
            if wait is not None:
                from consul_tpu.ratelimit import retry_after_header
                return self._plain(
                    conn, 429, b"rate limit exceeded",
                    meta=b"X-Consul-Reason: rate-limited\r\n"
                         b"Retry-After: "
                         + retry_after_header(wait).encode()
                         + b"\r\n")
        t0 = _time.perf_counter()
        wall0 = _time.time()
        telemetry.incr_counter(("http", verb.lower()))
        ttok = None
        if trace_id:
            # bind the request trace so a server-backed kv_set's
            # forwarded apply carries it to the leader; garbage ids
            # are dropped (trace.sanitize_id), not minted-over — the
            # untraced hot path must stay span-free
            from consul_tpu import trace
            trace_id = trace.sanitize_id(trace_id)
            if trace_id:
                ttok = trace.set_current(trace_id)
        try:
            authz = srv.acl.resolve(token or q.get("token")
                                    or srv.tokens.user_token() or None)
            if verb == "GET":
                if not authz.key_read(key):
                    return self._plain(conn, 403, b"Permission denied")
                meta = self._read_meta()
                e = store.kv_get(key)
                if not e:
                    return self._plain(conn, 404, b"",
                                       index=store.index, meta=meta)
                # serialized-row cache: hot keys re-read far more often
                # than they change (the VERDICT's "cache serialized hot
                # responses" lever); keyed by modify_index so any write
                # to the key invalidates naturally
                ck = (key, e["modify_index"], bool(e.get("session")))
                hit = self._row_cache.get(ck)
                if hit is None:
                    from consul_tpu.api.http import _kv_json
                    hit = json.dumps([_kv_json(e)]).encode()
                    if len(self._row_cache) > 4096:
                        self._row_cache.clear()
                    self._row_cache[ck] = hit
                return self._raw_json(conn, hit, index=store.index,
                                      meta=meta)
            if verb == "PUT":
                if not authz.key_write(key):
                    return self._plain(conn, 403, b"Permission denied")
                if len(body) > srv.kv_max_value_size:
                    return self._plain(
                        conn, 413,
                        b"Request body too large: value size exceeds "
                        + str(srv.kv_max_value_size).encode()
                        + b" limit")
                ok, idx = store.kv_set(
                    key, body, flags=flags, cas=cas,
                    acquire=q.get("acquire"), release=q.get("release"))
                return self._json(conn, ok, index=idx)
            # DELETE
            if not authz.key_write(key):
                return self._plain(conn, 403, b"Permission denied")
            ok, idx = store.kv_delete(key, recurse=False, cas=cas)
            return self._json(conn, ok, index=idx)
        except Exception as e:
            # overload/unavailable outcomes keep their distinct status
            # + machine-readable reason on the hot path too (ISSUE 13):
            # an admission NACK must reach the client as the same 503
            # X-Consul-Reason the legacy front shapes
            from consul_tpu.api.http import _overload_response
            mapped = _overload_response(e)
            try:
                msg = f"{type(e).__name__}: {e}".encode()
                if mapped is not None:
                    code, rsn = mapped
                    self._write(conn, code, msg,
                                b"application/octet-stream", None,
                                meta=b"X-Consul-Reason: "
                                     + rsn.encode() + b"\r\n")
                else:
                    # store/raft faults (leader loss mid-write, ...)
                    # must reach the client as the legacy 500, not a
                    # connection reset
                    telemetry.incr_counter(("http", "fastfront_error"),
                                           labels={"kind": "request"})
                    self._write(conn, 500, msg,
                                b"application/octet-stream", None)
            except OSError:
                pass
            return True
        finally:
            telemetry.measure_since(("http", "latency"), t0)
            if trace_id:
                from consul_tpu import trace
                if ttok is not None:
                    trace.reset(ttok)
                trace.record("http.request", trace_id, wall0,
                             _time.perf_counter() - t0,
                             verb=verb, path=path, fast=True)

    # ------------------------------------------------------------ writers

    _REASON = {200: b"OK", 403: b"Forbidden", 404: b"Not Found",
               413: b"Payload Too Large",
               429: b"Too Many Requests",
               500: b"Internal Server Error",
               503: b"Service Unavailable"}

    def _read_meta(self) -> bytes:
        """The consistency headers every read response carries
        (readplane.headers(), pre-encoded for the raw writer)."""
        rp = self._api.readplane
        lc = rp.last_contact_ms()
        return (b"X-Consul-KnownLeader: "
                + (b"true" if rp.known_leader() else b"false")
                + b"\r\nX-Consul-LastContact: "
                + str(int(lc) if lc != float("inf") else 0).encode()
                + b"\r\n")

    def _write(self, conn, code: int, payload: bytes, ctype: bytes,
               index: Optional[int], meta: bytes = b"") -> bool:
        idx = index if index is not None else self._api.store.index
        conn.sendall(
            _HOP + str(code).encode() + b" "
            + self._REASON.get(code, b"X") + b"\r\n"
            b"Content-Type: " + ctype + b"\r\n"
            b"Content-Length: " + str(len(payload)).encode() + b"\r\n"
            b"X-Consul-Index: " + str(idx).encode() + b"\r\n"
            + meta + b"\r\n"
            + payload)
        return True

    def _json(self, conn, obj, index: Optional[int] = None,
              meta: bytes = b"") -> bool:
        return self._write(conn, 200, json.dumps(obj).encode(),
                           b"application/json", index, meta)

    def _raw_json(self, conn, payload: bytes,
                  index: Optional[int] = None,
                  meta: bytes = b"") -> bool:
        return self._write(conn, 200, payload, b"application/json",
                           index, meta)

    def _plain(self, conn, code: int, payload: bytes,
               index: Optional[int] = None,
               meta: bytes = b"") -> bool:
        return self._write(conn, code, payload,
                           b"application/octet-stream", index, meta)
