"""Distributed Lock and Semaphore over sessions + KV.

Client-side coordination primitives mirroring the reference's
api/lock.go (Lock/Unlock/Destroy with session heartbeat semantics) and
api/semaphore.go (N-holder semaphore: per-contender session keys plus a
CAS-guarded coordination key holding the holder set).

Both block on KV blocking queries rather than polling hot: losing a
race parks on `?index=` until the lock prefix changes.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

# reference defaults (api/lock.go:32-43, semaphore.go:30-41)
DEFAULT_SESSION_TTL = "15s"
LOCK_FLAG = 0x2DDCCD18
SEMAPHORE_FLAG = 0xE0F69A2BAA414DE0


class LockError(Exception):
    pass


class Lock:
    """Mutual exclusion on one KV key (api/lock.go)."""

    def __init__(self, client, key: str, value: bytes = b"",
                 session_ttl: str = DEFAULT_SESSION_TTL,
                 retry_time: float = 5.0):
        self.client = client
        self.key = key
        self.value = value
        self.session_ttl = session_ttl
        # pause between acquire retries inside a lock-delay window
        # (api/lock.go DefaultLockRetryTime) — without it the delay
        # window becomes a full-speed kv_put/kv_get hot loop
        self.retry_time = retry_time
        self.session: Optional[str] = None

    @property
    def held(self) -> bool:
        return self.session is not None

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        """Take the lock; blocks (KV watch, not hot polling) until held
        or `timeout`.  Returns False on timeout / non-blocking miss."""
        if self.held:
            raise LockError("lock already held by this handle")
        sid = self.client.session_create(ttl=self.session_ttl)
        deadline = None if timeout is None else time.time() + timeout
        try:
            while True:
                if self.client.kv_put(self.key, self.value,
                                      flags=LOCK_FLAG, acquire=sid):
                    self.session = sid
                    return True
                if not blocking:
                    break
                row, idx = self.client.kv_get(self.key)
                if row is not None and not row.get("Session"):
                    # free key yet acquire failed → lock-delay window:
                    # back off before retrying (DefaultLockRetryTime)
                    pause = self.retry_time
                    if deadline is not None:
                        pause = min(pause,
                                    max(0.0, deadline - time.time()))
                        if pause <= 0:
                            break
                    time.sleep(pause)
                    continue
                remaining = None if deadline is None \
                    else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    break
                wait = "10s" if remaining is None \
                    else f"{max(1, int(remaining))}s"
                self.client.kv_get(self.key, index=idx, wait=wait)
                if deadline is not None and time.time() >= deadline:
                    break
            self.client.session_destroy(sid)
            return False
        except Exception:
            self.client.session_destroy(sid)
            raise

    def release(self) -> None:
        """Unlock (api/lock.go Unlock): release the key, keep it."""
        if not self.held:
            raise LockError("lock not held")
        sid, self.session = self.session, None
        self.client.kv_put(self.key, b"", release=sid)
        self.client.session_destroy(sid)

    def destroy(self) -> None:
        """Delete the lock key if free (api/lock.go Destroy)."""
        if self.held:
            raise LockError("release before destroy")
        row, _ = self.client.kv_get(self.key)
        if row is not None and not row.get("Session"):
            self.client.kv_delete(self.key)

    def __enter__(self) -> "Lock":
        if not self.acquire():
            raise LockError(f"could not acquire {self.key!r}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class Semaphore:
    """N-holder semaphore on a KV prefix (api/semaphore.go).

    Layout: `<prefix>/<session>` contender keys (session-bound) and
    `<prefix>/.lock` — a CAS-guarded JSON {"Limit": N, "Holders": [...]}
    coordination document."""

    def __init__(self, client, prefix: str, limit: int,
                 value: bytes = b"", session_ttl: str = DEFAULT_SESSION_TTL):
        if limit < 1:
            raise ValueError("semaphore limit must be >= 1")
        self.client = client
        self.prefix = prefix.rstrip("/")
        self.limit = limit
        self.value = value
        self.session_ttl = session_ttl
        self.session: Optional[str] = None

    # ----------------------------------------------------------- internals

    @property
    def _lock_key(self) -> str:
        return f"{self.prefix}/.lock"

    def _contender_key(self, sid: str) -> str:
        return f"{self.prefix}/{sid}"

    def _live_contenders(self) -> List[str]:
        rows = self.client.kv_list(f"{self.prefix}/")
        return [r["Session"] for r in rows
                if r.get("Session")
                and not r["Key"].endswith("/.lock")]

    def _read_doc(self):
        row, idx = self.client.kv_get(self._lock_key)
        if row is None:
            return {"Limit": self.limit, "Holders": []}, 0, idx
        doc = json.loads(row["Value"] or b"{}")
        doc.setdefault("Holders", [])
        return doc, row["ModifyIndex"], idx

    # ------------------------------------------------------------- public

    @property
    def held(self) -> bool:
        return self.session is not None

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        if self.held:
            raise LockError("semaphore already held by this handle")
        sid = self.client.session_create(ttl=self.session_ttl)
        # contender key binds our liveness to the session: if we die,
        # the session invalidation deletes it and others prune us
        if not self.client.kv_put(self._contender_key(sid), self.value,
                                  flags=SEMAPHORE_FLAG, acquire=sid):
            self.client.session_destroy(sid)
            raise LockError("could not create contender entry")
        deadline = None if timeout is None else time.time() + timeout
        try:
            while True:
                doc, cas, idx = self._read_doc()
                live = set(self._live_contenders())
                # prune dead holders (semaphore.go pruneDeadHolders)
                holders = [h for h in doc["Holders"] if h in live]
                if len(holders) < doc.get("Limit", self.limit):
                    holders.append(sid)
                    new = json.dumps(
                        {"Limit": doc.get("Limit", self.limit),
                         "Holders": holders}).encode()
                    if self.client.kv_put(self._lock_key, new, cas=cas):
                        self.session = sid
                        return True
                    continue      # CAS race: re-read and retry
                if not blocking:
                    break
                remaining = None if deadline is None \
                    else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    break
                wait = "10s" if remaining is None \
                    else f"{max(1, int(remaining))}s"
                self.client.kv_list_blocking(f"{self.prefix}/",
                                             index=idx, wait=wait)
                if deadline is not None and time.time() >= deadline:
                    break
            self.client.kv_delete(self._contender_key(sid))
            self.client.session_destroy(sid)
            return False
        except Exception:
            # best-effort contender cleanup: session release alone
            # leaves the orphan key in KV forever
            try:
                self.client.kv_delete(self._contender_key(sid))
            except Exception:
                pass
            self.client.session_destroy(sid)
            raise

    def release(self) -> None:
        if not self.held:
            raise LockError("semaphore not held")
        sid, self.session = self.session, None
        # drop ourselves from the holder doc under CAS
        while True:
            doc, cas, _ = self._read_doc()
            if sid not in doc["Holders"]:
                break
            doc["Holders"] = [h for h in doc["Holders"] if h != sid]
            if self.client.kv_put(self._lock_key,
                                  json.dumps(doc).encode(), cas=cas):
                break
        self.client.kv_delete(self._contender_key(sid))
        self.client.session_destroy(sid)

    def __enter__(self) -> "Semaphore":
        if not self.acquire():
            raise LockError(f"could not acquire {self.prefix!r}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()
