"""Distributed Lock and Semaphore over sessions + KV.

Client-side coordination primitives mirroring the reference's
api/lock.go (Lock/Unlock/Destroy with session heartbeat semantics) and
api/semaphore.go (N-holder semaphore: per-contender session keys plus a
CAS-guarded coordination key holding the holder set).

Both block on KV blocking queries rather than polling hot: losing a
race parks on `?index=` until the lock prefix changes.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from http.client import HTTPException

from consul_tpu.api.client import ApiError

# what a best-effort cleanup call can see from the HTTP client: an
# HTTP-level error (ApiError), a socket/connection failure (OSError,
# incl. urllib.error.URLError), or a torn response (HTTPException,
# e.g. IncompleteRead when the agent dies mid-body)
_TRANSPORT_ERRORS = (ApiError, OSError, HTTPException)

# reference defaults (api/lock.go:32-43, semaphore.go:30-41)
DEFAULT_SESSION_TTL = "15s"
LOCK_FLAG = 0x2DDCCD18
SEMAPHORE_FLAG = 0xE0F69A2BAA414DE0


class LockError(Exception):
    pass


class _SessionHeartbeat:
    """Background session renewal at TTL/2 (api/lock.go renewSession /
    session.RenewPeriodic): without it the leader's TTL reaper destroys
    the session mid-hold — the lock silently releases while the handle
    still reports held, and a parked waiter's own session dies so its
    acquire loop can never succeed.

    Transient renew errors (connection reset, a 500 during leader
    election) are retried up to the TTL budget; only a definitive
    session-not-found — or retries exhausted — marks the hold LOST,
    which flips the owning handle's `held` to False (the reference
    closes lockSession/leaderCh for the same reason: the holder must
    learn it no longer owns the lock)."""

    def __init__(self, client, sid: str, ttl: str):
        import threading
        self.client = client
        self.sid = sid
        ttl_s = _ttl_seconds(ttl)
        period = max(0.5, ttl_s / 2.0)
        retry = max(0.25, period / 2.0)
        # loss must be declared BEFORE the reaper can fire: first failed
        # attempt lands at last_renew + period, each hurried retry adds
        # `retry`, so 2 failures marks lost at period + retry = 0.75*ttl
        # < ttl — never a window where held=True past the reap point
        max_failures = 2
        self.lost = threading.Event()
        self._stop = threading.Event()

        def loop():
            failures = 0
            wait = period
            while not self._stop.wait(wait):
                try:
                    renewed = self.client.session_renew(self.sid)
                    if not renewed:
                        self.lost.set()
                        return
                    failures = 0
                    wait = period
                except Exception as e:
                    from consul_tpu import telemetry
                    # consul.session.renew_failed: every missed renew
                    # is a step toward a lost lock — count them
                    telemetry.incr_counter(("session", "renew_failed"))
                    if isinstance(e, ApiError) and e.code == 404:
                        self.lost.set()    # session reaped: definitive
                        return
                    failures += 1
                    if failures >= max_failures:
                        self.lost.set()
                        return
                    wait = retry                   # hurried retry

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def _ttl_seconds(ttl: str) -> float:
    from consul_tpu.utils.duration import parse_duration
    return parse_duration(ttl, 15.0)


def _wait_str(remaining: Optional[float], default: str = "10s") -> str:
    """Blocking-wait duration honoring sub-second budgets."""
    if remaining is None:
        return default
    return f"{max(0.05, remaining):.3f}s"


class Lock:
    """Mutual exclusion on one KV key (api/lock.go)."""

    def __init__(self, client, key: str, value: bytes = b"",
                 session_ttl: str = DEFAULT_SESSION_TTL,
                 retry_time: float = 5.0):
        self.client = client
        self.key = key
        self.value = value
        self.session_ttl = session_ttl
        # pause between acquire retries inside a lock-delay window
        # (api/lock.go DefaultLockRetryTime) — without it the delay
        # window becomes a full-speed kv_put/kv_get hot loop
        self.retry_time = retry_time
        self.session: Optional[str] = None

    @property
    def held(self) -> bool:
        """False once the heartbeat reports the session lost — the
        holder must not keep acting as owner after the reaper fired."""
        hb = getattr(self, "_heartbeat", None)
        if hb is not None and hb.lost.is_set():
            return False
        return self.session is not None

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        """Take the lock; blocks (KV watch, not hot polling) until held
        or `timeout`.  Returns False on timeout / non-blocking miss."""
        if self.held:
            raise LockError("lock already held by this handle")
        sid = self.client.session_create(ttl=self.session_ttl)
        hb = _SessionHeartbeat(self.client, sid, self.session_ttl)
        deadline = None if timeout is None else time.time() + timeout
        try:
            while True:
                if self.client.kv_put(self.key, self.value,
                                      flags=LOCK_FLAG, acquire=sid):
                    self.session = sid
                    self._heartbeat = hb
                    return True
                if not blocking:
                    break
                row, idx = self.client.kv_get(self.key)
                if row is not None and not row.get("Session"):
                    # free key yet acquire failed → lock-delay window:
                    # back off before retrying (DefaultLockRetryTime)
                    pause = self.retry_time
                    if deadline is not None:
                        pause = min(pause,
                                    max(0.0, deadline - time.time()))
                        if pause <= 0:
                            break
                    time.sleep(pause)
                    continue
                remaining = None if deadline is None \
                    else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    break
                self.client.kv_get(self.key, index=idx,
                                   wait=_wait_str(remaining))
                if deadline is not None and time.time() >= deadline:
                    break
            hb.stop()
            self.client.session_destroy(sid)
            return False
        except Exception:
            hb.stop()
            self.client.session_destroy(sid)
            raise

    def release(self) -> None:
        """Unlock (api/lock.go Unlock): release the key, keep it.
        A LOST hold (session reaped under us) still cleans up quietly —
        __exit__ must not mask the caller's exception with LockError."""
        if self.session is None:
            raise LockError("lock not held")
        sid, self.session = self.session, None
        hb = getattr(self, "_heartbeat", None)
        lost = hb is not None and hb.lost.is_set()
        if hb is not None:
            hb.stop()
            self._heartbeat = None
        if not lost:
            self.client.kv_put(self.key, b"", release=sid)
        try:
            self.client.session_destroy(sid)
        except _TRANSPORT_ERRORS:
            pass   # already reaped (or agent gone) — expected here

    def destroy(self) -> None:
        """Delete the lock key if free (api/lock.go Destroy)."""
        if self.held:
            raise LockError("release before destroy")
        row, _ = self.client.kv_get(self.key)
        if row is not None and not row.get("Session"):
            self.client.kv_delete(self.key)

    def __enter__(self) -> "Lock":
        if not self.acquire():
            raise LockError(f"could not acquire {self.key!r}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class Semaphore:
    """N-holder semaphore on a KV prefix (api/semaphore.go).

    Layout: `<prefix>/<session>` contender keys (session-bound) and
    `<prefix>/.lock` — a CAS-guarded JSON {"Limit": N, "Holders": [...]}
    coordination document."""

    def __init__(self, client, prefix: str, limit: int,
                 value: bytes = b"", session_ttl: str = DEFAULT_SESSION_TTL):
        if limit < 1:
            raise ValueError("semaphore limit must be >= 1")
        self.client = client
        self.prefix = prefix.rstrip("/")
        self.limit = limit
        self.value = value
        self.session_ttl = session_ttl
        self.session: Optional[str] = None

    # ----------------------------------------------------------- internals

    @property
    def _lock_key(self) -> str:
        return f"{self.prefix}/.lock"

    def _contender_key(self, sid: str) -> str:
        return f"{self.prefix}/{sid}"

    def _live_contenders(self) -> List[str]:
        rows = self.client.kv_list(f"{self.prefix}/")
        return [r["Session"] for r in rows
                if r.get("Session")
                and not r["Key"].endswith("/.lock")]

    def _read_doc(self):
        row, idx = self.client.kv_get(self._lock_key)
        if row is None:
            return {"Limit": self.limit, "Holders": []}, 0, idx
        doc = json.loads(row["Value"] or b"{}")
        doc.setdefault("Holders", [])
        return doc, row["ModifyIndex"], idx

    # ------------------------------------------------------------- public

    @property
    def held(self) -> bool:
        hb = getattr(self, "_heartbeat", None)
        if hb is not None and hb.lost.is_set():
            return False
        return self.session is not None

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        if self.held:
            raise LockError("semaphore already held by this handle")
        sid = self.client.session_create(ttl=self.session_ttl)
        # contender key binds our liveness to the session: if we die,
        # the session invalidation deletes it and others prune us
        if not self.client.kv_put(self._contender_key(sid), self.value,
                                  flags=SEMAPHORE_FLAG, acquire=sid):
            self.client.session_destroy(sid)
            raise LockError("could not create contender entry")
        hb = _SessionHeartbeat(self.client, sid, self.session_ttl)
        deadline = None if timeout is None else time.time() + timeout
        try:
            while True:
                doc, cas, idx = self._read_doc()
                live = set(self._live_contenders())
                # prune dead holders (semaphore.go pruneDeadHolders)
                holders = [h for h in doc["Holders"] if h in live]
                if len(holders) < doc.get("Limit", self.limit):
                    holders.append(sid)
                    new = json.dumps(
                        {"Limit": doc.get("Limit", self.limit),
                         "Holders": holders}).encode()
                    if self.client.kv_put(self._lock_key, new, cas=cas):
                        self.session = sid
                        self._heartbeat = hb
                        return True
                    continue      # CAS race: re-read and retry
                if not blocking:
                    break
                remaining = None if deadline is None \
                    else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    break
                self.client.kv_list_blocking(f"{self.prefix}/",
                                             index=idx,
                                             wait=_wait_str(remaining))
                if deadline is not None and time.time() >= deadline:
                    break
            hb.stop()
            self.client.kv_delete(self._contender_key(sid))
            self.client.session_destroy(sid)
            return False
        except Exception:
            # best-effort contender cleanup: session release alone
            # leaves the orphan key in KV forever
            hb.stop()
            try:
                self.client.kv_delete(self._contender_key(sid))
            except _TRANSPORT_ERRORS:
                pass   # best-effort: the outer raise carries the cause
            try:
                self.client.session_destroy(sid)
            except _TRANSPORT_ERRORS:
                pass   # best-effort: the outer raise carries the cause
            raise

    def release(self) -> None:
        if self.session is None:
            raise LockError("semaphore not held")
        sid, self.session = self.session, None
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            hb.stop()
            self._heartbeat = None
        # drop ourselves from the holder doc under CAS (needed even
        # after a lost session: the doc entry is ours to prune)
        while True:
            doc, cas, _ = self._read_doc()
            if sid not in doc["Holders"]:
                break
            doc["Holders"] = [h for h in doc["Holders"] if h != sid]
            if self.client.kv_put(self._lock_key,
                                  json.dumps(doc).encode(), cas=cas):
                break
        self.client.kv_delete(self._contender_key(sid))
        try:
            self.client.session_destroy(sid)
        except _TRANSPORT_ERRORS:
            pass   # already reaped (or agent gone) — expected here

    def __enter__(self) -> "Semaphore":
        if not self.acquire():
            raise LockError(f"could not acquire {self.prefix!r}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()
