from consul_tpu.api.http import ApiServer
from consul_tpu.api.client import Client

__all__ = ["ApiServer", "Client"]
