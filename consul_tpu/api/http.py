"""HTTP API — the Consul /v1 surface over (StateStore, GossipOracle).

Route shape and JSON field names mirror the reference's HTTP API
(route table agent/http_register.go:4-127; handler plumbing
agent/http.go:115 registerEndpoint).  Implemented routes:

  status:    /v1/status/leader /v1/status/peers
  agent:     /v1/agent/self /v1/agent/members /v1/agent/metrics
             /v1/agent/events[?since=&wait=] /v1/agent/profile
             /v1/agent/service/register /v1/agent/service/deregister/<id>
             /v1/agent/check/register /v1/agent/check/(pass|warn|fail)/<id>
             /v1/agent/force-leave/<node> /v1/agent/leave
  catalog:   /v1/catalog/register /v1/catalog/deregister /v1/catalog/nodes
             /v1/catalog/services /v1/catalog/service/<n> /v1/catalog/node/<n>
  health:    /v1/health/service/<name>[?passing&tag=&near=]
             /v1/health/node/<node> /v1/health/state/<state>
  kv:        /v1/kv/<key> GET/PUT/DELETE with ?recurse ?keys ?raw ?cas=
             ?flags= ?acquire= ?release= ?separator= and blocking ?index=&wait=
  session:   /v1/session/create /destroy/<id> /renew/<id> /info/<id> /list /node/<n>
  coordinate:/v1/coordinate/nodes /v1/coordinate/node/<node>
  event:     /v1/event/fire/<name> /v1/event/list
  txn:       /v1/txn
  snapshot:  /v1/snapshot (GET save / PUT restore)

Blocking queries honor ?index= & ?wait= (units "10s"/"1m") and every
response carries X-Consul-Index (agent/consul/rpc.go:806 blockingQuery).
"""

from __future__ import annotations

import base64
import hashlib
import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler
from typing import Optional

from consul_tpu.acl.resolver import ACLResolver
from consul_tpu.bexpr import BexprError
from consul_tpu.catalog.store import StateStore
from consul_tpu.oracle import GossipOracle
from consul_tpu import locks, servicemgr
from consul_tpu.version import VERSION


def _parse_wait(val: str) -> float:
    from consul_tpu.utils.duration import parse_duration
    return parse_duration(val, 10.0)


def _overload_response(e: BaseException):
    """(status, X-Consul-Reason) for overload/unavailable exceptions,
    None for everything else (which stays the generic 500).  Lazy
    imports: the handler's exception path must not couple module
    import order."""
    from consul_tpu.ratelimit import ApplyRejectedError
    from consul_tpu.server import NoLeaderError
    if isinstance(e, ApplyRejectedError):
        # queue_full / deadline — the admission NACK: definitely not
        # committed, and a 503 the client maps to ambiguous=False
        return 503, e.reason.replace("_", "-")
    if isinstance(e, NoLeaderError):
        return 503, "no-leader"
    return None


class NullOracle:
    """Inert oracle for server-backed ApiServers with no gossip device
    attached (the pure control-plane deployment shape)."""

    tick = 0
    n_nodes = 0

    def members(self):
        return []

    def coordinate(self, name):
        raise KeyError(name)

    def leave(self, name):
        pass

    def fire_event(self, name, payload, origin):
        return "0"

    def event_list(self):
        return []

    def event_coverage(self, event_id):
        return 0.0

    def sort_by_rtt(self, origin, names):
        return list(names)

    def keyring_list(self):
        return {"Keys": {}, "PrimaryKeys": {}, "NumNodes": 0}

    def keyring_install(self, key):
        pass

    def keyring_use(self, key):
        raise KeyError("no keyring")

    def keyring_remove(self, key):
        pass


class ApiServer:
    """Threaded HTTP server bound to an ephemeral or fixed port.

    `store` may be a bare StateStore or a raft-replicated Server (the
    duck-typed write surface): reads hit the local replica, writes go
    through raft with leader forwarding, and ?consistent reads barrier
    via Server.consistent_index (agent/consul/rpc.go consistentRead)."""

    def __init__(self, store: StateStore, oracle: GossipOracle = None,
                 node_name: str = "node0", host: str = "127.0.0.1",
                 port: int = 0, dc: str = "dc1",
                 acl_resolver: Optional[ACLResolver] = None,
                 local=None, checks=None):
        self.store = store
        self.oracle = oracle if oracle is not None else NullOracle()
        self.node_name = node_name
        self.dc = dc
        # no resolver → ACLs disabled (resolve() returns allow-all)
        self.acl = acl_resolver or ACLResolver(store, enabled=False)
        # agent-endpoint backing: LocalState + CheckManager when wired by
        # an Agent (the reference's /v1/agent/* writes hit local state and
        # anti-entropy pushes to the catalog; without an agent the routes
        # fall through to direct store writes)
        self.local = local
        self.checks = checks
        from consul_tpu.prepared_query import QueryExecutor
        self.query_executor = QueryExecutor(
            self.store, self.oracle, node_name=node_name, dc=dc)
        # runtime-updatable agent tokens (agent/token/store.go); Agent
        # rebinds this with a persistent store when it has a data_dir
        from consul_tpu.token_store import TokenStore
        self.tokens = TokenStore()
        # set by Agent.from_config: PUT /v1/agent/reload re-reads config
        self.reload_fn = None
        # secondary-DC wiring: an acl.replication.Replicator whose
        # status GET /v1/acl/replication reports (None = replication
        # not enabled on this agent)
        self.acl_replicator = None
        # secondary-DC replication SET (ISSUE 18): every live
        # Replicator (tokens/intentions/config-entries/federation-
        # states) — statuses served at /v1/internal/ui/replication,
        # scraped into federation_view + debug bundles
        self.replicators = []
        # self-sizing write limits: the DynamicLimitController when
        # armed (--rate-limit dynamic=1); exposed so introspection can
        # report the CURRENT walked write_rate
        self.limit_controller = None
        # multi-DC: a WanRouter enables ?dc= forwarding + query failover
        # (agent/consul/rpc.go:658 forwardDC)
        self.router = None
        # wanfed: when on, ?dc= forwarding dials the target DC's mesh
        # gateway from replicated federation states instead of a direct
        # route (consul_tpu/wanfed.py; wanfed.go:39)
        self.wan_fed_via_gateways = False
        # /debug/pprof analogues served only when explicitly enabled
        # (agent/http.go enable_debug gate)
        self.enable_debug = False
        # OIDC code-flow plumbing (ssoauth shape): auth-url mints a
        # single-use state; callback exchanges the code for an ID token
        # through `oidc_token_fetcher` — INJECTABLE because the real
        # exchange is an HTTPS POST to the IdP's token endpoint, which
        # this rig's zero-egress policy blocks (tests inject a local
        # fetcher; production would set one that can reach the IdP)
        self.oidc_token_fetcher = None
        self._oidc_states: dict = {}
        self._oidc_lock = locks.make_lock("http.oidc")
        # the agent's gRPC ADS port when one is bound (-1 = disabled);
        # surfaced via /v1/agent/self so `connect envoy -bootstrap`
        # can point a stock Envoy at it
        self.grpc_port = -1
        # pre-raft payload guards: 512 KiB KV value cap
        # (kv_max_value_size, performance.mdx:149) and 64-op txn cap
        # (agent/txn_endpoint.go maxTxnOps); both reject with 413
        # BEFORE anything reaches the replicated log
        self.kv_max_value_size = 512 * 1024
        # ui_config.metrics_proxy (reloadable): {base_url,
        # path_allowlist, add_headers} — empty dict = disabled
        self.ui_metrics_proxy: dict = {}
        # cluster federation (consul_tpu/introspect.py): the HTTP
        # addresses of every server in this cluster, served back as one
        # merged view at /v1/internal/ui/cluster-metrics.  None =
        # endpoint disabled (same stance as the metrics proxy); set
        # programmatically or via tools/server_proc.py --cluster-http.
        # A fixed configured set, never caller-supplied URLs — the
        # agent must not become an open scrape proxy (SSRF).
        self.cluster_nodes: Optional[list] = None
        # WAN federation view (consul_tpu/introspect.federation_view):
        # DC -> list/map of that DC's server HTTP addresses, served as
        # one merged multi-DC view at /v1/internal/ui/federation.
        # Same SSRF stance as cluster_nodes: a fixed configured set
        # (tools/server_proc.py --federation-http), never the caller's.
        self.federation_nodes: Optional[dict] = None
        # the datacenter dimension of every visibility sample/span
        # (ISSUE 15): the store mints indexes, this server knows the DC
        vis = getattr(self.store, "visibility", None)
        if vis is not None:
            vis.dc = dc
        self.txn_max_ops = 64
        # guards the per-proxy xDS delta payload caches: handler
        # threads race on insert/evict (ThreadingHTTPServer)
        self._xds_cache_lock = locks.make_lock("http.xds_cache")
        # Connect CA (lazy: cert generation costs entropy/CPU at boot)
        self._ca = None
        self._ca_lock = locks.make_lock("http.ca")
        # streaming read backend: materialized views over store events
        # (?cached serving — agent/submatview); the request-keyed Cache
        # serves Cache-Control max-age reads (agent/cache)
        from consul_tpu.submatview import ViewStore
        pub = getattr(self.store, "publisher", None)
        self.view_store = ViewStore(pub) if pub is not None else None
        from consul_tpu.cache import Cache as AgentCache
        self.agent_cache = AgentCache()
        self._register_cache_types()
        # read plane (consul_tpu/readplane.py): consistency-mode
        # resolution for every read route — ?stale serves the local
        # replica (lag-bounded by ?max_stale), ?consistent barriers,
        # and default-mode reads on a follower forward to the leader
        # WHEN the fleet HTTP map is configured (cluster_nodes doubles
        # as the leader-forward route table; without it a standalone
        # node serves locally, the pre-readplane behavior)
        from consul_tpu.readplane import ReadPlane
        self.readplane = ReadPlane(
            store, node_name=node_name,
            cluster_nodes_fn=lambda: self.cluster_nodes)
        # ingress rate limiting (consul_tpu/ratelimit.py, the
        # reference's agent/consul/rate role): per-client/per-route-
        # class token buckets consulted by BOTH fronts — over-limit
        # requests shed fast with 429 + Retry-After + X-Consul-Reason.
        # Disabled by default (one attribute read on the hot path);
        # operators configure via ratelimit.configure() /
        # tools/server_proc.py --rate-limit, observing in permissive
        # mode before enforcing.
        from consul_tpu.ratelimit import RateLimiter
        self.ratelimit = RateLimiter()
        handler = _make_handler(self)
        # Custom threaded front: hot KV ops on a minimal parser, every
        # other route replayed through `handler` byte-for-byte — the
        # BaseHTTPRequestHandler core alone ceilings ~5.2k req/s on one
        # core, under the reference's absolute GET bar
        # (consul_tpu/api/fastfront.py)
        from consul_tpu.api.fastfront import FastKVServer
        self.httpd = FastKVServer((host, port), handler, self)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _fetch_health(self, key: str):
        name, tag, passing = key.split("\x00")
        rows = self.store.health_service_nodes(
            name, tag=tag or None, passing_only=passing == "True")
        return rows, self.store.index

    def _ui_services_summary(self):
        """Pre-ACL per-service summary rows (agent/ui_endpoint.go
        UIServices / cache-types service_dump role); the route filters
        by the requester's authorizer after the cache."""
        st = self.store
        kind_map = st.service_kind_map()
        out = []
        for name, tags in st.services().items():
            rows = st.health_service_nodes(name)
            statuses = [
                ("critical" if any(c["status"] == "critical"
                                   for c in r["checks"])
                 else "warning" if any(c["status"] == "warning"
                                       for c in r["checks"])
                 else "passing") for r in rows]
            kinds = kind_map.get(name, {""}) - {""}
            out.append({
                "Name": name, "Tags": tags,
                "Kind": next(iter(kinds)) if kinds else "",
                "InstanceCount": len(rows),
                "ChecksPassing": statuses.count("passing"),
                "ChecksWarning": statuses.count("warning"),
                "ChecksCritical": statuses.count("critical"),
            })
        return out

    def _ui_nodes_summary(self):
        """Pre-ACL per-node summary rows (UINodes role)."""
        st = self.store
        out = []
        for n in st.nodes():
            checks = st.node_checks(n["node"])
            out.append({
                "Node": n["node"], "Address": n["address"],
                "Checks": {
                    "passing": sum(1 for c in checks
                                   if c["status"] == "passing"),
                    "warning": sum(1 for c in checks
                                   if c["status"] == "warning"),
                    "critical": sum(1 for c in checks
                                    if c["status"] == "critical")},
            })
        return out

    def _register_cache_types(self) -> None:
        """The typed cache registry (agent/cache-types/: the reference
        registers 23 entries — discovery chain, CA leaf/roots,
        intention match, gateway services, catalog reads...).  Each
        fetcher returns (value, index); the Cache layers TTL,
        background refresh, and Cache-Control max-age semantics on
        top.  Keys are the request discriminators, '\\x00'-joined."""
        reg = self.agent_cache.register_type
        st = self.store

        reg("health_services",
            lambda key, mi, t: self._fetch_health(key), ttl=600.0)
        reg("catalog_services",
            lambda key, mi, t: (st.services(), st.index), ttl=600.0)
        reg("catalog_service_nodes",
            lambda key, mi, t: (st.service_nodes(key), st.index),
            ttl=600.0)
        reg("catalog_nodes",
            lambda key, mi, t: (st.nodes(), st.index), ttl=600.0)
        reg("node_services",
            lambda key, mi, t: (st.node_services(key), st.index),
            ttl=600.0)
        reg("health_connect",
            lambda key, mi, t: (st.health_connect_nodes(key),
                                st.index), ttl=600.0)
        reg("health_checks",
            lambda key, mi, t: (
                [c for r in st.health_service_nodes(key)
                 for c in r["checks"] if c.get("service_id")],
                st.index), ttl=600.0)
        reg("connect_ca_roots",
            lambda key, mi, t: (self.ca.roots(), st.index), ttl=600.0)
        # leaf certs route through proxycfg's leaf cache so a fetch
        # never re-signs while the cached cert is fresh (the reference
        # ConnectCALeaf type blocks on rotation the same way)
        reg("connect_ca_leaf",
            lambda key, mi, t: (self.proxycfg.get_leaf(key), st.index),
            ttl=3600.0)

        def _fetch_intention_match(key, mi, t):
            from consul_tpu.connect import intentions as imod
            # maxsplit: a NUL smuggled into the service name must not
            # blow up the unpack (the name is opaque past the first
            # separator)
            by, name = key.split("\x00", 1)
            return (imod.match_order(st.intention_list(), name, by),
                    st.index)

        reg("intention_match", _fetch_intention_match, ttl=600.0)

        def _fetch_chain(key, mi, t):
            from consul_tpu.discoverychain import compile_chain
            return compile_chain(st, key, dc=self.dc), st.index

        reg("discovery_chain", _fetch_chain, ttl=600.0)

        def _fetch_gateway_services(key, mi, t):
            from consul_tpu import gateways as gmod
            return gmod.gateway_services(st, key), st.index

        reg("gateway_services", _fetch_gateway_services, ttl=600.0)

        def _fetch_resolved_config(key, mi, t):
            # key = service name [\x00 upstream,...] — the central
            # defaults merge the ServiceManager consumes
            # (agent/cache-types/resolved_service_config.go)
            parts = key.split("\x00")
            ups = tuple(u for u in parts[1:] if u)
            return (servicemgr.resolve_service_config(
                st, parts[0], ups), st.index)

        reg("resolved_service_config", _fetch_resolved_config,
            ttl=600.0)

        def _fetch_intention_upstreams(key, mi, t):
            # services `key` may dial per intentions — what a
            # transparent proxy must watch
            # (agent/cache-types/intention_upstreams.go)
            return ([e["name"] for e in st.intention_topology(
                key, downstreams=False,
                default_allow=self.default_allow)], st.index)

        reg("intention_upstreams", _fetch_intention_upstreams,
            ttl=600.0)

        def _fetch_service_topology(key, mi, t):
            return (st.service_topology(
                key, default_allow=self.default_allow), st.index)

        reg("service_topology", _fetch_service_topology, ttl=600.0)
        reg("federation_states",
            lambda key, mi, t: (st.federation_state_list(), st.index),
            ttl=600.0)
        reg("config_entries",
            lambda key, mi, t: (st.config_entry_list(key or None),
                                st.index), ttl=600.0)
        # round-4 batch: the remaining reference cache types
        # (agent/cache-types/) so ?cached is uniform across routes —
        # every fetcher returns PRE-ACL data; the route applies the
        # requester's filter after the cache, so entries are shareable
        # across tokens exactly like the reference's
        reg("catalog_datacenters",
            lambda key, mi, t: (
                self.router.datacenters() if self.router is not None
                else [self.dc], st.index), ttl=600.0)
        reg("service_dump",
            lambda key, mi, t: (self._ui_services_summary(), st.index),
            ttl=600.0)
        reg("node_dump",
            lambda key, mi, t: (self._ui_nodes_summary(), st.index),
            ttl=600.0)
        reg("checks_in_state",
            lambda key, mi, t: (st.checks_in_state(key), st.index),
            ttl=600.0)
        reg("intention_list",
            lambda key, mi, t: (st.intention_list(), st.index),
            ttl=600.0)

        def _fetch_prepared_query(key, mi, t):
            # rsplit: the NAME is opaque and may contain a smuggled
            # NUL — only the trailing discriminators are ours
            name, limit, near = key.rsplit("\x00", 2)
            res = self.query_executor.execute(
                name, limit=int(limit or 0), near=near or None)
            return res, st.index

        reg("prepared_query", _fetch_prepared_query, ttl=600.0)

    def cached_read(self, type_name: str, key: str, headers, q):
        """(value, index, 'HIT'|'MISS') when the request OPTED INTO
        cached serving (?cached + Cache-Control max-age — a bare
        max-age header is a generic HTTP idiom, not consent to stale
        agent-cache data); None → serve the normal path.  Blocking
        (?index) and ?consistent requests always take the live path —
        a consistent read served from cache would readmit exactly the
        staleness the flag excludes (the reference rejects
        cached+consistent as conflicting)."""
        if "cached" not in q or "index" in q or "consistent" in q:
            return None
        cc = headers.get("Cache-Control", "")
        m = re.search(r"max-age=(\d+)", cc)
        if not m:
            return None
        val, idx, hit = self.agent_cache.get(
            type_name, key, max_age=float(m.group(1)))
        return val, idx, ("HIT" if hit else "MISS")

    @property
    def default_allow(self) -> bool:
        """Intention/RBAC default: follows the ACL default policy when
        ACLs are enabled, else allow (one definition - intentions check,
        authorize, and xDS RBAC all share it)."""
        return self.acl.default_policy == "allow" \
            if getattr(self.acl, "enabled", False) else True

    @property
    def ca(self):
        # double-checked under a lock: two concurrent first requests must
        # not build two CAManagers with different trust domains
        if self._ca is None:
            with self._ca_lock:
                if self._ca is None:
                    from consul_tpu.connect.ca import CAManager
                    self._ca = CAManager(dc=self.dc)
        return self._ca

    _proxycfg = None
    _proxycfg_lock = locks.make_lock("http.proxycfg")

    @property
    def proxycfg(self):
        if self._proxycfg is None:
            with self._proxycfg_lock:
                if self._proxycfg is None:
                    from consul_tpu.proxycfg import Manager
                    self._proxycfg = Manager(
                        self.store, self.ca, dc=self.dc,
                        default_allow=self.default_allow)
        return self._proxycfg

    def attach_router(self, router) -> None:
        """Join a federation: register this DC's surface and wire the
        prepared-query executor's cross-DC failover hooks."""
        from consul_tpu.router import DcHandle
        self.router = router
        handle = DcHandle(self.dc, self.store,
                          query_executor=self.query_executor)
        handle.http_address = self.address
        router.register(handle)
        self.query_executor.remote_execute = router.execute_query
        self.query_executor.dc_order = router.datacenters

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # shutdown() handshakes with serve_forever — calling it on a
        # never-started server parks forever on the internal event
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)


def _make_handler(srv: ApiServer):
    store, oracle = srv.store, srv.oracle

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Nagle + delayed-ACK between request and response writes adds
        # ~40ms per keep-alive round-trip; small-RPC servers always
        # disable it (the reference's net/http does the same)
        disable_nagle_algorithm = True

        def log_message(self, *a):  # quiet
            pass

        # ------------------------------------------------------------ helpers

        def _q(self):
            parsed = urllib.parse.urlparse(self.path)
            path = urllib.parse.unquote(parsed.path)
            # trailing slashes are significant for KV keys (prefix reads)
            if not path.startswith("/v1/kv/"):
                path = path.rstrip("/")
            return path, dict(
                urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n else b""

        # store index a parked blocking query was woken at — set by
        # _block, consumed by _send so the response write emits the
        # apply->flush visibility stage (per-connection handler state,
        # reset per request)
        _vis_index = None

        def _send(self, obj, code: int = 200, raw: bytes | None = None,
                  index: int | None = None, ctype: str | None = None,
                  extra_headers: dict | None = None):
            payload = raw if raw is not None else json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype or (
                             "application/octet-stream" if raw is not None
                             else "application/json"))
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("X-Consul-Index",
                             str(index if index is not None else store.index))
            extra = extra_headers or {}
            if getattr(self, "command", "") == "GET":
                # consistency metadata on every read response
                # (agent/http.go setMeta); a leader-forwarded response
                # passes the LEADER's values through extra_headers —
                # they describe the node that executed the read
                for k, v in srv.readplane.headers().items():
                    if k not in extra:
                        self.send_header(k, v)
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)
            vis, self._vis_index = self._vis_index, None
            if vis is not None and index == vis:
                # the watcher's response bytes are on the wire: the
                # end of the commit-to-visibility pipeline
                store.visibility.stage("flush", vis)

        def _err(self, code: int, msg: str, reason: str = "",
                 retry_after: float = None):
            """Error response; `reason` stamps the machine-readable
            X-Consul-Reason header (ISSUE 13: 429 rate-limited vs 503
            no-leader/queue-full/deadline/max-stale vs 500 internal —
            clients and chaos checkers discriminate on it instead of
            grepping bodies), `retry_after` the RFC 9110 Retry-After
            hint in seconds."""
            extra = {}
            if reason:
                extra["X-Consul-Reason"] = reason
            if retry_after is not None:
                from consul_tpu.ratelimit import retry_after_header
                extra["Retry-After"] = retry_after_header(retry_after)
            self._send(None, code, raw=msg.encode(),
                       extra_headers=extra or None)

        def _consistent(self, q) -> None:
            """?consistent: leader barrier, then wait for the LOCAL
            replica to catch up to the barrier index — serving straight
            from a lagging follower would readmit the staleness the flag
            excludes (rpc.go consistentRead).  500s when no leader."""
            if "consistent" in q and hasattr(store, "consistent_index"):
                idx = store.consistent_index()
                if store.index < idx:
                    got = store.wait_for(idx - 1, timeout=5.0)
                    if got < idx:
                        # serving a stale read after an acked write is
                        # the violation ?consistent excludes: fail loud
                        # (consistentRead errors; clients retry on 500)
                        raise RuntimeError(
                            "consistent read: replica catch-up timed "
                            "out")

        def _block(self, q, *watches) -> int:
            """Honor ?index/?wait before evaluating the read.

            `watches` are (topic, key) specs for prefix-granular wakeups
            (store.wait_on) — an unrelated write does not wake this query;
            with no watches it degrades to the coarse any-write wait
            (blockingQuery, agent/consul/rpc.go:806)."""
            self._consistent(q)
            if "index" in q:
                # consul.rpc.query counts CLIENT blocking queries, one
                # per request (rpc.go:815) — counted here rather than
                # in store.wait_* so internal waits (consistent-read
                # catch-up, hash-watch wakeups) don't inflate it
                from consul_tpu import telemetry
                telemetry.incr_counter(("rpc", "query"))
                wait = _parse_wait(q.get("wait", "300s"))
                pre = store.index
                if watches:
                    idx = store.wait_on(watches, int(q["index"]),
                                        timeout=wait)
                else:
                    idx = store.wait_for(int(q["index"]), timeout=wait)
                if idx > pre:
                    # a write LANDED while this query was parked (not a
                    # stale-cursor immediate return, whose apply could
                    # be arbitrarily old): sample the wakeup stage and
                    # arm _send to sample the flush — the two ends of
                    # the watch-delivery half of the visibility SLI
                    store.visibility.stage("wakeup", idx)
                    self._vis_index = idx
                return idx
            return store.index

        def _forbid(self) -> bool:
            """403 like the reference's acl.ErrPermissionDenied path."""
            self._err(403, "Permission denied")
            return True

        def _filtered(self, q, rows):
            """?filter= expression filtering over the response rows
            (go-bexpr; parseFilter callers in agent/agent_endpoint.go,
            catalog/health endpoints).  The expression was pre-compiled
            in _dispatch so malformed filters 400 BEFORE any blocking
            wait, not after it."""
            flt = self._filter
            if flt is None:
                return rows
            if isinstance(rows, dict):
                return {k: v for k, v in rows.items() if flt(v)}
            return [r for r in rows if flt(r)]

        def _check_update_allowed(self, check_id: str) -> bool:
            """A service check is writable with service:write on its
            service (vetCheckUpdate, agent/acl.go)."""
            chk = next((c for c in store.node_checks(srv.node_name)
                        if c["check_id"] == check_id), None)
            if not chk or not chk["service_id"]:
                return False
            svc = next((s for s in store.node_services(srv.node_name)
                        if s["id"] == chk["service_id"]), None)
            return bool(svc) and self.authz.service_write(svc["name"])

        def _check_visible(self, node: str, chk: dict,
                           svc_cache: dict | None = None) -> bool:
            """aclFilter for checks: service checks need service:read on
            their service; node checks ride the node:read gate.
            `svc_cache` maps node -> {service_id: name} across one request
            (avoids a store scan per check)."""
            sid = chk.get("service_id", "")
            if not sid:
                return True
            if svc_cache is not None:
                by_id = svc_cache.get(node)
                if by_id is None:
                    by_id = {s["id"]: s["name"]
                             for s in store.node_services(node)}
                    svc_cache[node] = by_id
                name = by_id.get(sid)
            else:
                svc = next((s for s in store.node_services(node)
                            if s["id"] == sid), None)
                name = svc["name"] if svc else None
            # unknown service id: fall back to the id as a name (agent
            # default check naming uses service:<id>)
            return self.authz.service_read(name if name else sid)

        def _session_node_write(self, sid: str) -> bool:
            sess = store.session_info(sid)
            return self.authz.session_write(
                sess["node"] if sess else srv.node_name)

        def _node_maintenance(self, enable: bool, reason: str) -> None:
            """Reserved `_node_maintenance` critical check toggles the
            whole node out of DNS/health results
            (agent.EnableNodeMaintenance / DisableNodeMaintenance)."""
            cid = "_node_maintenance"
            if enable:
                if srv.local is not None:
                    srv.local.add_check(cid, "Node Maintenance Mode",
                                        status="critical", output=reason)
                    srv.local.sync_changes(store)
                else:
                    store.register_check(srv.node_name, cid,
                                         "Node Maintenance Mode",
                                         status="critical", output=reason)
            else:
                if srv.local is not None and cid in srv.local.checks():
                    srv.local.remove_check(cid)
                    srv.local.sync_changes(store)
                else:
                    store.deregister_check(srv.node_name, cid)

        def _service_maintenance(self, sid: str, enable: bool,
                                 reason: str) -> None:
            cid = f"_service_maintenance:{sid}"
            if enable:
                if srv.local is not None and \
                        sid in srv.local.services():
                    srv.local.add_check(cid, "Service Maintenance Mode",
                                        status="critical", service_id=sid,
                                        output=reason)
                    srv.local.sync_changes(store)
                else:
                    store.register_check(srv.node_name, cid,
                                         "Service Maintenance Mode",
                                         status="critical",
                                         service_id=sid, output=reason)
            else:
                if srv.local is not None and cid in srv.local.checks():
                    srv.local.remove_check(cid)
                    srv.local.sync_changes(store)
                else:
                    store.deregister_check(srv.node_name, cid)

        def _aggregate_service_status(self, sid: str) -> str:
            """Worst-of aggregation over node + service checks
            (agent_endpoint.go AgentHealthServiceByID): maintenance
            trumps critical trumps warning trumps passing."""
            st = "passing"
            for c in store.node_checks(srv.node_name):
                if c["service_id"] not in ("", sid):
                    continue
                cid = c["check_id"]
                if cid == "_node_maintenance" or \
                        cid == f"_service_maintenance:{sid}":
                    return "maintenance"
                st = _worse_status(st, c["status"])
            return st

        # ------------------------------------------- agent-endpoint helpers

        def _agent_register_service(self, sid: str, body: dict) -> None:
            """Write through local state + AE when wired; otherwise the
            store directly (structs.ServiceDefinition handling,
            agent/agent_endpoint.go AgentRegisterService).  Sidecar
            (Kind=connect-proxy) registrations carry their Proxy config
            to the catalog directly — proxycfg discovers them there."""
            name = body.get("Name", sid)
            if body.get("Kind") in ("connect-proxy", "mesh-gateway",
                                    "ingress-gateway",
                                    "terminating-gateway"):
                # mesh data-plane services (sidecars + the three gateway
                # kinds) register store-side with Kind/Proxy intact —
                # proxycfg discovers them in the catalog.  The full
                # proxy surface is kept (config/mode/transparent_proxy/
                # expose — structs.ConnectProxyConfig) so the
                # ServiceManager merge and the expose/tproxy listener
                # shapes have their inputs.
                proxy_raw = body.get("Proxy") or {}
                proxy = {
                    "destination_service": proxy_raw.get(
                        "DestinationServiceName", ""),
                    "destination_service_id": proxy_raw.get(
                        "DestinationServiceID", ""),
                    "local_service_address": proxy_raw.get(
                        "LocalServiceAddress", "127.0.0.1"),
                    "local_service_port": proxy_raw.get(
                        "LocalServicePort", 0),
                    "config": proxy_raw.get("Config") or {},
                    "mode": proxy_raw.get("Mode", ""),
                    "transparent_proxy": _lower_keys(
                        proxy_raw.get("TransparentProxy") or {}),
                    "expose": _lower_keys(proxy_raw.get("Expose")
                                          or {}),
                    "mesh_gateway": _lower_keys(
                        proxy_raw.get("MeshGateway") or {}),
                    "upstreams": [
                        {"destination_name": u.get(
                            "DestinationName", ""),
                         "local_bind_port": u.get("LocalBindPort", 0),
                         "local_bind_address": u.get(
                             "LocalBindAddress", "127.0.0.1"),
                         # opaque per-upstream config (escape hatches
                         # envoy_listener_json/envoy_cluster_json ride
                         # here — agent/xds/config.go)
                         "config": u.get("Config") or {}}
                        for u in proxy_raw.get("Upstreams") or []],
                }
                store.register_service(
                    srv.node_name, sid, name,
                    port=body.get("Port", 0),
                    tags=body.get("Tags") or [],
                    meta=body.get("Meta") or {},
                    address=body.get("Address", ""),
                    kind=body["Kind"], proxy=proxy)
                # checks attached to the sidecar register store-side
                # AND arm their runners, notifying the store directly
                # (sidecars bypass local state, so runner results can't
                # ride the AE path)
                checks = list(body.get("Checks") or [])
                if body.get("Check"):
                    checks.append(body["Check"])
                for i, chk in enumerate(checks):
                    cid = chk.get("CheckID") or \
                        f"service:{sid}" + (f":{i+1}" if i else "")
                    store.register_check(
                        srv.node_name, cid, chk.get("Name") or cid,
                        status=chk.get("Status", "critical"),
                        service_id=sid)
                    defn = _check_defn(chk)
                    if srv.checks is not None and defn:
                        def _store_notify(check_id, status,
                                          output=""):
                            try:
                                store.update_check(
                                    srv.node_name, check_id,
                                    status, output=output)
                            except KeyError:
                                pass
                        if defn.get("alias_node") or \
                                defn.get("alias_service"):
                            # sidecar alias-of-parent check (the
                            # second default check sidecar_service.go
                            # attaches) — mirrors the parent's
                            # aggregate status store-side
                            from consul_tpu.checks import CheckAlias
                            srv.checks.add(CheckAlias(
                                cid, _store_notify, store,
                                defn.get("alias_node")
                                or srv.node_name,
                                defn.get("alias_service", "")))
                            continue
                        runner = srv.checks.from_definition(cid, defn)
                        if runner is not None:
                            runner.notify = _store_notify
                            srv.checks.add(runner)
                return
            if srv.local is not None:
                srv.local.add_service(
                    sid, name, port=body.get("Port", 0),
                    tags=body.get("Tags") or [], meta=body.get("Meta") or {},
                    address=body.get("Address", ""))
            else:
                store.register_service(
                    srv.node_name, sid, name, port=body.get("Port", 0),
                    tags=body.get("Tags") or [], meta=body.get("Meta") or {},
                    address=body.get("Address", ""))
            checks = list(body.get("Checks") or [])
            if body.get("Check"):
                checks.append(body["Check"])
            for i, chk in enumerate(checks):
                default_cid = f"service:{sid}" + (f":{i+1}" if i else "")
                cid = chk.get("CheckID") or default_cid
                self._agent_register_check(cid, chk, sid)
            if srv.local is not None:
                srv.local.sync_changes(store)
            # connect.sidecar_service {}: expand into a fully-defaulted
            # connect-proxy registration with an allocated port
            # (agent/sidecar_service.go:12) and register it like any
            # other sidecar
            expanded = servicemgr.expand_sidecar(
                body, store.node_services(srv.node_name))
            if expanded is not None:
                s_sid, s_body = expanded
                self._agent_register_service(s_sid, s_body)

        def _agent_service_json(self, sid: str, row: dict,
                                resolved: dict | None = None) -> dict:
            """One agent service in the reference's api.AgentService
            wire shape, with a connect-proxy's config RESOLVED against
            central defaults (service_manager.go merge) and a
            ContentHash over the rendered definition (AgentService
            hash-blocking)."""
            out = {
                "ID": sid,
                "Service": row["name"],
                "Tags": row.get("tags") or [],
                "Meta": row.get("meta") or {},
                "Port": row.get("port", 0),
                "Address": row.get("address", ""),
                "Datacenter": srv.dc,
            }
            kind = row.get("kind", "")
            if kind:
                out["Kind"] = kind
            proxy = row.get("proxy") or {}
            if kind in ("connect-proxy", "ingress-gateway",
                        "terminating-gateway", "mesh-gateway"):
                dest = proxy.get("destination_service", "")
                merged = servicemgr.merged_proxy(
                    store, proxy, dest or row["name"], resolved)
                out["Proxy"] = _proxy_json(merged)
            out["ContentHash"] = hashlib.sha256(
                json.dumps(out, sort_keys=True).encode()
            ).hexdigest()[:16]
            return out

        def _drop_service_runners(self, sid: str) -> None:
            """Stop check runners armed for a STORE-side service before
            its rows go away (the local-state path removes its own;
            without this, sidecar TCP/alias runners outlive their
            service and poll a deregistered target forever)."""
            if srv.checks is None:
                return
            for c in store.node_checks(srv.node_name):
                if c.get("service_id") == sid:
                    srv.checks.remove(c["check_id"])

        def _agent_register_check(self, cid: str, body: dict,
                                  service_id: str = "") -> None:
            name = body.get("Name") or cid
            status = body.get("Status", "critical")
            defn = _check_defn(body)
            if srv.local is not None:
                srv.local.add_check(cid, name, status=status,
                                    service_id=service_id,
                                    output=body.get("Notes", ""))
                if srv.checks is not None and defn:
                    if defn.get("alias_node") or defn.get("alias_service"):
                        from consul_tpu.checks import CheckAlias
                        srv.checks.add(CheckAlias(
                            cid, srv.checks.notify, store,
                            defn.get("alias_node") or srv.node_name,
                            defn.get("alias_service", "")))
                    else:
                        runner = srv.checks.from_definition(cid, defn)
                        if runner is not None:
                            srv.checks.add(runner)
                srv.local.sync_changes(store)
            else:
                store.register_check(srv.node_name, cid, name,
                                     status=status, service_id=service_id)

        # ------------------------------------------------------------- verbs

        def do_GET(self):
            self._route("GET")

        def do_PUT(self):
            self._route("PUT")

        def do_DELETE(self):
            self._route("DELETE")

        def do_POST(self):
            self._route("PUT")

        def _route(self, verb: str):
            from consul_tpu import telemetry, trace
            import time as _time
            t0 = _time.perf_counter()
            wall0 = _time.time()
            # keep-alive handlers persist across requests: a blocking
            # query that armed the flush stage but errored before its
            # _send must not leak the stamp into the next request
            self._vis_index = None
            # trace: minted here at the API entry point unless the
            # caller (another agent's ?dc= hop, or an instrumented
            # client) already carries a VALID one — the ID then rides
            # leader forwarding and blocking-query retries unchanged
            tid = trace.sanitize_id(
                self.headers.get("X-Consul-Trace-Id")) \
                or trace.new_trace_id()
            ttok = trace.set_current(tid)
            tpath = "<parse-error>"
            try:
                path, q = self._q()
                tpath = path
                telemetry.incr_counter(("http", verb.lower()))
                # token: X-Consul-Token header > Bearer > ?token= (the
                # reference's header/QueryOptions order, agent/http.go
                # parseToken)
                token = self.headers.get("X-Consul-Token")
                if not token:
                    auth = self.headers.get("Authorization", "")
                    if auth.startswith("Bearer "):
                        token = auth[len("Bearer "):].strip()
                token = token or q.get("token")
                self.token = token
                # tokenless requests run under the agent's default-token
                # slot before falling to anonymous (parseToken order:
                # request token > agent default token > anonymous)
                self.authz = srv.acl.resolve(
                    token or srv.tokens.user_token() or None)
                # ingress rate limiting (ISSUE 13): shed over-limit
                # data-plane requests FAST, before any store work —
                # the fastfront checks its own hot path, this covers
                # the legacy front AND every fastfront fallback.
                # Client identity = ACL token when present (the
                # reference keys its limits the same way), else the
                # peer address.
                rl = srv.ratelimit
                if rl.mode != "disabled":
                    from consul_tpu import ratelimit as _rlmod
                    rc = _rlmod.route_class(verb, path)
                    if rc is not None:
                        wait = rl.check(
                            token or self.client_address[0], rc)
                        if wait is not None:
                            self._err(429, "rate limit exceeded",
                                      reason="rate-limited",
                                      retry_after=wait)
                            telemetry.measure_since(
                                ("http", "latency"), t0)
                            return
                if self._dispatch(verb, path, q):
                    telemetry.measure_since(("http", "latency"), t0)
                    return
                self._err(404, f"no route {verb} {path}")
            except BrokenPipeError:
                pass
            except BexprError as e:
                try:
                    self._err(400, f"invalid filter: {e}")
                except OSError:
                    pass   # client went away mid-error-response
            except Exception as e:  # pragma: no cover
                # overload/unavailable outcomes get their own status +
                # machine-readable reason (ISSUE 13): an admission
                # NACK (definitely-not-committed) and a leaderless
                # write are 503s a client can discriminate, not 500s
                mapped = _overload_response(e)
                try:
                    if mapped is not None:
                        code, rsn = mapped
                        self._err(code, f"{type(e).__name__}: {e}",
                                  reason=rsn)
                    else:
                        # consul.http.request_error: 500s an operator
                        # can alarm on (the handler must never die)
                        telemetry.incr_counter(("http",
                                                "request_error"))
                        self._err(500, f"{type(e).__name__}: {e}")
                except OSError:
                    pass   # client went away mid-error-response
            finally:
                trace.record("http.request", tid, wall0,
                             _time.perf_counter() - t0,
                             verb=verb, path=tpath)
                trace.reset(ttok)

        # ---------------------------------------------------------- dispatch

        def _forward_dc(self, verb: str, path: str, q) -> bool:
            """?dc= forwarding: replay the request against the target
            DC's HTTP surface (the reference's forwardDC network hop,
            rpc.go:658).  Unknown DC → 500 like structs.ErrNoDCPath."""
            import urllib.error
            import urllib.request
            from consul_tpu.router import NoPathError
            dc = q.pop("dc")
            addr = None
            via_gateway = False
            if srv.wan_fed_via_gateways:
                # wanfed: the remote DC is reachable only through its
                # mesh gateway, located from replicated federation
                # states (wanfed.go; gateway_locator.go)
                from consul_tpu.wanfed import gateway_address
                gw = gateway_address(store, dc)
                if gw is not None:
                    addr = f"http://{gw[0]}:{gw[1]}"
                    via_gateway = True
            if addr is None and srv.router is not None:
                try:
                    handle = srv.router.handle(dc)
                except NoPathError as e:
                    self._err(500, str(e))
                    return True
                addr = getattr(handle, "http_address", None)
            if addr is None:
                self._err(500, f"No path to datacenter: {dc!r}")
                return True
            qs = urllib.parse.urlencode(q)
            # path was percent-decoded by _q(); re-quote for the hop
            url = addr + urllib.parse.quote(path) + (f"?{qs}" if qs else "")
            body = self._body() if verb in ("PUT", "POST") else None
            req = urllib.request.Request(url, data=body, method=verb)
            if self.token:
                req.add_header("X-Consul-Token", self.token)
            # consul.rpc.cross-dc (rpc.go forwardDC's metric) + trace
            # propagation so the remote DC's spans join this trace
            from consul_tpu import telemetry, trace
            telemetry.incr_counter(("rpc", "cross-dc"),
                                   labels={"dc": dc})
            if via_gateway:
                # the WAN hop proper: this request leaves the local
                # DC through the remote DC's mesh gateway (ISSUE 15
                # SLI — cross-DC traffic by (src, dst) pair)
                telemetry.incr_counter(("wanfed", "forward"),
                                       labels={"src_dc": srv.dc,
                                               "dst_dc": dc})
            tid = trace.current_trace()
            if tid:
                req.add_header("X-Consul-Trace-Id", tid)
            try:
                # the wanfed.forward span is the local-DC leg of the
                # cross-DC trace: same id as the remote DC's spans, so
                # ?trace_id= on EITHER side shows its half of the hop
                with trace.span("wanfed.forward" if via_gateway
                                else "rpc.forward_dc",
                                src_dc=srv.dc, dst_dc=dc):
                    with urllib.request.urlopen(req,
                                                timeout=330.0) as resp:
                        raw = resp.read()
                        self._send(None, resp.status, raw=raw,
                                   index=int(resp.headers.get(
                                       "X-Consul-Index") or 0),
                                   ctype=resp.headers.get(
                                       "Content-Type"))
            except urllib.error.HTTPError as e:
                self._err(e.code, e.read().decode(errors="replace"))
            return True

        # dc-forwardable surfaces (the reference forwards catalog-style
        # RPCs only; /v1/agent/* and /v1/acl/* are strictly local).
        # /v1/internal/replication/ rides the same WAN forward: a
        # secondary's replicators reach the primary THROUGH the mesh
        # gateways, so severing a gateway link severs replication —
        # the failure mode the divergence checker exists to observe.
        _DC_FORWARDABLE = ("/v1/kv/", "/v1/catalog/", "/v1/health/",
                           "/v1/query", "/v1/session/", "/v1/coordinate/",
                           "/v1/event/", "/v1/txn",
                           "/v1/internal/replication/")

        # set per-request in _dispatch; class default covers error
        # paths that _send before resolution ran
        _read_mode = "default"

        def _forward_leader(self, verb: str, path: str, q) -> bool:
            """Default-consistency read on a follower: replay against
            the leader's HTTP surface (the read half of ForwardRPC,
            rpc.go:549).  The X-Consul-Read-Forwarded hop marker stops
            a stale leader hint from looping; the leader's consistency
            headers pass through — they describe the node that
            actually executed the read."""
            import urllib.error
            import urllib.request
            addr = srv.readplane.leader_http()
            if addr is None:
                self._err(503, "No cluster leader", reason="no-leader")
                return True
            qs = urllib.parse.urlencode(q)
            url = addr + urllib.parse.quote(path) \
                + (f"?{qs}" if qs else "")
            req = urllib.request.Request(url, method=verb)
            req.add_header("X-Consul-Read-Forwarded", "1")
            if self.token:
                req.add_header("X-Consul-Token", self.token)
            from consul_tpu import trace
            tid = trace.current_trace()
            if tid:
                req.add_header("X-Consul-Trace-Id", tid)
            try:
                with urllib.request.urlopen(req, timeout=330.0) as resp:
                    raw = resp.read()
                    meta = {k: resp.headers[k] for k in
                            ("X-Consul-KnownLeader",
                             "X-Consul-LastContact")
                            if k in resp.headers}
                    self._send(None, resp.status, raw=raw,
                               index=int(resp.headers.get(
                                   "X-Consul-Index") or 0),
                               ctype=resp.headers.get("Content-Type"),
                               extra_headers=meta)
            except urllib.error.HTTPError as e:
                self._err(e.code, e.read().decode(errors="replace"))
            except OSError as e:
                # the leader died mid-forward: surface it as the
                # unavailable error the caller retries on
                self._err(503, f"leader read forward failed: {e}",
                          reason="no-leader")
            return True

        def _dispatch(self, verb: str, path: str, q) -> bool:
            # compile ?filter= up front: a malformed expression must 400
            # immediately, not after a 5-minute blocking wait
            if "filter" in q:
                from consul_tpu.bexpr import compile_filter
                self._filter = compile_filter(q["filter"])
            else:
                self._filter = None
            if q.get("dc") not in (None, "", srv.dc) \
                    and path.startswith(self._DC_FORWARDABLE):
                if srv.router is None and not srv.wan_fed_via_gateways:
                    self._err(500,
                              f"No path to datacenter: {q['dc']!r}")
                    return True
                return self._forward_dc(verb, path, q)
            q.pop("dc", None)
            # read plane: resolve the consistency mode for every GET
            # (consul_tpu/readplane.py) — stale serves below from the
            # local replica, a violated ?max_stale bound rejects here,
            # and a default-mode read on a follower forwards to the
            # leader when the fleet HTTP map is configured
            self._read_mode = "default"
            if verb == "GET":
                dec = srv.readplane.resolve(path, q, self.headers)
                self._read_mode = dec.mode
                if dec.action == "reject":
                    self._err(dec.code, dec.message,
                              reason=dec.reason.replace("_", "-"))
                    return True
                if dec.action == "forward":
                    return self._forward_leader(verb, path, q)
            if path.startswith("/v1/kv/"):
                return self._kv(verb, path[len("/v1/kv/"):], q)
            if path.startswith(("/v1/acl/login", "/v1/acl/logout",
                                "/v1/acl/auth-method",
                                "/v1/acl/binding-rule",
                                "/v1/acl/oidc/")):
                return self._authmethods(verb, path, q)
            if path.startswith("/v1/acl"):
                return self._acl(verb, path, q)
            if path in ("/ui", "/ui/", "/", "") and verb == "GET":
                # "" is "/" after the trailing-slash strip in _q()
                # single-page dashboard (the reference serves its Ember
                # app at /ui via agent/uiserver)
                from consul_tpu.ui import PAGE
                self._send(None, raw=PAGE.encode(),
                           ctype="text/html; charset=utf-8")
                return True
            if path.startswith("/debug/pprof") and verb == "GET":
                # profiling surface (agent/http.go installs pprof under
                # enable_debug; ACL-gated on operator:read)
                if not srv.enable_debug:
                    self._err(404, "debug endpoints disabled "
                              "(enable_debug)")
                    return True
                if not self.authz.operator_read():
                    return self._forbid()
                from consul_tpu import debug as dbg
                if path == "/debug/pprof/goroutine":
                    self._send(None, raw=dbg.thread_dump().encode(),
                               ctype="text/plain; charset=utf-8")
                    return True
                if path == "/debug/pprof/profile":
                    secs = min(30.0, float(q.get("seconds", 1) or 1))
                    self._send(dbg.sample_profile(seconds=secs))
                    return True
                if path == "/debug/pprof/heap":
                    self._send(dbg.heap_snapshot())
                    return True
                self._err(404, f"no pprof route {path}")
                return True
            if path == "/v1/status/leader" and verb == "GET":
                # real raft state when server-backed (Status.Leader);
                # the standalone-agent default keeps the classic shape
                raft = getattr(store, "raft", None)
                if raft is not None:
                    # Server.leader_id owns the self-vs-remote fold
                    lid = store.leader_id
                    addrs = getattr(store.transport, "addresses", {}) \
                        if hasattr(store, "transport") else {}
                    addr = addrs.get(lid)
                    self._send(f"{addr[0]}:{addr[1]}" if addr
                               else (f"{lid}:8300" if lid else ""))
                    return True
                self._send("127.0.0.1:8300")
                return True
            if path == "/v1/status/peers" and verb == "GET":
                raft = getattr(store, "raft", None)
                if raft is not None:
                    ids = [store.node_id] + list(raft.peers)
                    addrs = getattr(store.transport, "addresses", {}) \
                        if hasattr(store, "transport") else {}
                    self._send([
                        f"{addrs[i][0]}:{addrs[i][1]}" if i in addrs
                        else f"{i}:8300" for i in sorted(set(ids))])
                    return True
                self._send(["127.0.0.1:8300"])
                return True
            if path == "/v1/agent/self" and verb == "GET":
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                self._send({"Config": {"NodeName": srv.node_name,
                                       "Datacenter": srv.dc,
                                       "Server": True,
                                       "Version": VERSION},
                            "DebugConfig": {
                                "GRPCPort": srv.grpc_port},
                            "xDS": {"Port": srv.grpc_port},
                            "Stats": {"sim_tick": oracle.tick,
                                      "sim_nodes": oracle.n_nodes}})
                return True
            if path == "/v1/agent/members" and verb == "GET":
                # aclFilter: members filter by node:read, not 403.
                # ?limit/?offset paginate (the sim targets N where a full
                # dump is not servable); ?segment= restricts to one LAN
                # segment pool (agent_endpoint.go AgentMembers segment)
                limit = max(0, int(q["limit"])) if "limit" in q else None
                offset = max(0, int(q.get("offset", 0) or 0))
                kwargs = {"limit": limit, "offset": offset}
                if "segment" in q:
                    if not hasattr(oracle, "segments"):
                        self._err(400, "agent has no network segments")
                        return True
                    kwargs["segment"] = q["segment"]
                try:
                    rows = oracle.members(**kwargs)
                except KeyError as e:
                    self._err(400, f"unknown segment: {e}")
                    return True
                self._send(self._filtered(
                    q, [_member_json(m) for m in rows
                        if self.authz.node_read(m["name"])]))
                return True
            if path == "/v1/operator/segment" and verb == "GET":
                # LAN segment listing (enterprise operator/segment)
                if not self.authz.operator_read():
                    return self._forbid()
                segs = oracle.segments() if hasattr(oracle, "segments") \
                    else [""]
                self._send(["<default>" if s == "" else s
                            for s in segs])
                return True
            if path == "/v1/agent/traces" and verb == "GET":
                # the trace-span ring buffer (consul_tpu/trace.py):
                # operator surface for `consul-tpu debug` and ad-hoc
                # "where did this write go" queries
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu import trace
                limit = int(q["limit"]) if "limit" in q else None
                since = int(q.get("since", 0) or 0)
                spans = trace.dump(limit=limit,
                                   trace_id=q.get("trace_id"),
                                   since=since)
                # forward-paging cursor (the /v1/agent/events shape):
                # X-Consul-Index echoes the last seq RETURNED, or the
                # ring horizon on an empty filtered page — everything
                # up to it was examined, so a poller (the WAN probe,
                # federation_view correlation) advances instead of
                # re-downloading the ring
                self._send(spans,
                           index=spans[-1].get("seq", 0) if spans
                           else max(since, trace.last_seq()))
                return True
            if path == "/v1/agent/events" and verb == "GET":
                # the flight-recorder journal (consul_tpu/flight.py):
                # ?since=<seq> cursor + blocking-query semantics — with
                # ?wait= the request parks on the recorder's condition
                # until a newer event lands (the monitor/blocking-query
                # hybrid the reference splits over /v1/event/list and
                # /v1/agent/monitor)
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu import flight
                rec = flight.default_recorder()
                since = int(q.get("since", 0) or 0)
                limit = int(q["limit"]) if "limit" in q else None
                flt = {"name": q.get("name"),
                       "severity": q.get("severity")}
                rows, horizon = rec.read_page(since=since, limit=limit,
                                              **flt)
                if "wait" in q and limit != 0:
                    # park until a MATCHING event exists (or timeout):
                    # waiting on "any event" once would instantly
                    # return empty pages while unrelated traffic keeps
                    # the journal busy — a filtered watch would
                    # busy-loop.  limit=0 can never match; answer now.
                    deadline = time.time() + _parse_wait(q["wait"])
                    while not rows and time.time() < deadline:
                        rec.wait(horizon, deadline - time.time())
                        rows, horizon = rec.read_page(
                            since=since, limit=limit, **flt)
                # the cursor header is the last seq actually RETURNED
                # (a ?limit= page never skips the still-pending rows
                # behind it); an EMPTY result advances to the horizon
                # the scan examined under the read lock — everything
                # up to it is known non-matching, and anything newer
                # raced in AFTER the scan so the next poll sees it
                self._send([{
                    "Seq": r["seq"], "Ts": r["ts"], "Name": r["name"],
                    "Severity": r["severity"], "Labels": r["labels"],
                    "TraceID": r["trace_id"], "Msg": r.get("msg", "")}
                    for r in rows],
                    index=rows[-1]["seq"] if rows
                    else max(since, horizon))
                return True
            if path == "/v1/agent/profile" and verb == "GET":
                # the always-on tick profiler (consul_tpu/profiler.py):
                # per-pass EMA table + recompile accounting — the live
                # sibling of tools/profile_swim.py's offline report
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu.profiler import default_profiler
                self._send(default_profiler().snapshot())
                return True
            if path == "/v1/agent/metrics" and verb == "GET":
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu import telemetry
                # a metrics scrape IS a host-sync checkpoint: pull the
                # device-side sim counters accumulated inside the jitted
                # tick into consul.serf.* gauges (one fetch, no per-tick
                # host round-trips)
                if hasattr(oracle, "publish_sim_metrics"):
                    try:
                        oracle.publish_sim_metrics()
                    except Exception:
                        # metrics must serve even mid-compile — but a
                        # failing sim publication is itself a signal
                        telemetry.incr_counter(
                            ("http", "sim_metrics_error"))
                # per-scrape live values — ONE extras dict feeds both
                # exposition forms, so the prometheus text serves the
                # same families as the JSON dump (sanitize-dedupe
                # applied by Registry.prometheus; the shared registry
                # is never mutated by a scrape)
                extras = {"consul.sim.tick": float(oracle.tick),
                          "consul.catalog.index": float(store.index)}
                if hasattr(oracle, "members_summary"):
                    extras.update(
                        {f"consul.members.{k}": float(v)
                         for k, v in oracle.members_summary().items()})
                if q.get("format") == "prometheus":
                    # the reference serves text exposition when
                    # prometheus retention is on (agent_endpoint.go
                    # AgentMetrics + lib/telemetry.go PrometheusOpts)
                    reg = telemetry.default_registry()
                    self._send(None,
                               raw=reg.prometheus(
                                   extra_gauges=extras).encode(),
                               ctype="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    return True
                out = telemetry.default_registry().dump()
                out["Gauges"] += [{"Name": n, "Value": v}
                                  for n, v in sorted(extras.items())]
                self._send(out)
                return True
            if path == "/v1/agent/monitor" and verb == "GET":
                # live log stream (logging/monitor/monitor.go): chunked
                # lines until the client goes away
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu.logging import (LEVELS, default_buffer,
                                                level_of)
                lvl = LEVELS.get(q.get("loglevel", "INFO").upper(), 2)
                mon = None
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def chunk(data: bytes):
                        self.wfile.write(f"{len(data):x}\r\n".encode()
                                         + data + b"\r\n")
                        self.wfile.flush()

                    # replay BEFORE registering the live sink (no dupes;
                    # the reference's monitor is best-effort on the gap)
                    # and honor the requested level on the replay too
                    for line in default_buffer().recent(64):
                        if level_of(line) >= lvl:
                            chunk(line.encode() + b"\n")
                    mon = default_buffer().monitor(
                        q.get("loglevel", "INFO"))
                    deadline = time.time() + _parse_wait(
                        q.get("wait", "30s"))
                    while time.time() < deadline:
                        for line in mon.lines(timeout=0.5):
                            chunk(line.encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionError):
                    pass
                finally:
                    if mon is not None:
                        mon.stop()
                return True
            if path == "/v1/internal/federation-states" and verb == "GET":
                # per-DC mesh gateway lists (federation_state_endpoint)
                if not self.authz.operator_read():
                    return self._forbid()
                feds, idx, state = self._cache_or_live(
                    "federation_states", "", q,
                    store.federation_state_list, ("federation", ""))
                self._send([{
                    "Datacenter": f["datacenter"],
                    "MeshGateways": f["mesh_gateways"],
                    "UpdatedAt": f.get("updated", ""),
                    "ModifyIndex": f.get("modify_index", 0)}
                    for f in feds], index=idx,
                    extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/internal/federation-state/([^/]+)",
                             path)
            if m and verb == "GET":
                if not self.authz.operator_read():
                    return self._forbid()
                idx = self._block(q, ("federation", m.group(1)))
                f = store.federation_state_get(m.group(1))
                if f is None:
                    self._err(404, "no federation state")
                    return True
                self._send({"Datacenter": f["datacenter"],
                            "MeshGateways": f["mesh_gateways"],
                            "UpdatedAt": f.get("updated", ""),
                            "ModifyIndex": f.get("modify_index", 0)},
                           index=idx)
                return True
            if m and verb == "PUT":
                if not self.authz.operator_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                store.federation_state_set(
                    m.group(1), body.get("MeshGateways") or [],
                    body.get("UpdatedAt", ""))
                self._send(True)
                return True
            if path == "/v1/operator/keyring":
                # gossip keyring management (operator_endpoint.go
                # KeyringOperation; keyring:read/write ACLs)
                if verb == "GET":
                    if not self.authz.keyring_read():
                        return self._forbid()
                    self._send([dict(oracle.keyring_list(),
                                     WAN=False, Datacenter=srv.dc)])
                    return True
                body = json.loads(self._body() or b"{}")
                key = body.get("Key", "")
                if not self.authz.keyring_write():
                    return self._forbid()
                # the dispatcher folds POST into PUT; the keyring verbs
                # genuinely differ, so use the raw request method
                raw_verb = self.command
                try:
                    if raw_verb == "POST":
                        oracle.keyring_install(key)
                    elif raw_verb == "PUT":
                        oracle.keyring_use(key)
                    elif raw_verb == "DELETE":
                        oracle.keyring_remove(key)
                    else:
                        return False
                except (KeyError, ValueError) as e:
                    self._err(400, str(e))
                    return True
                self._send(None)
                return True
            if path == "/v1/operator/autopilot/health" and verb == "GET":
                if not self.authz.operator_read():
                    return self._forbid()
                ap = getattr(store, "autopilot", None)
                if ap is None:
                    self._err(400, "not a server-backed agent")
                    return True
                # match the clock driving tick(): virtual under the test
                # cluster, wall-clock in live deployments
                now = getattr(store.raft, "_now", None) or time.time()
                servers = ap.server_health(now)
                self._send({"Healthy": all(s["Healthy"] for s in servers),
                            "FailureTolerance": ap.failure_tolerance(now),
                            "Servers": servers})
                return True
            if path == "/v1/operator/autopilot/configuration":
                ap = getattr(store, "autopilot", None)
                if ap is None:
                    self._err(400, "not a server-backed agent")
                    return True
                if verb == "GET":
                    if not self.authz.operator_read():
                        return self._forbid()
                    c = ap.config
                    self._send({
                        "CleanupDeadServers": c.cleanup_dead_servers,
                        "LastContactThreshold":
                            f"{c.last_contact_threshold}s",
                        "ServerStabilizationTime":
                            f"{c.server_stabilization_time}s",
                    })
                    return True
                if verb == "PUT":
                    if not self.authz.operator_write():
                        return self._forbid()
                    body = json.loads(self._body() or b"{}")
                    c = ap.config
                    if "CleanupDeadServers" in body:
                        c.cleanup_dead_servers = \
                            bool(body["CleanupDeadServers"])
                    if "LastContactThreshold" in body:
                        c.last_contact_threshold = _parse_wait(
                            str(body["LastContactThreshold"]))
                    if "ServerStabilizationTime" in body:
                        c.server_stabilization_time = _parse_wait(
                            str(body["ServerStabilizationTime"]))
                    self._send(True)
                    return True
            if path == "/v1/operator/raft/configuration" and verb == "GET":
                if not self.authz.operator_read():
                    return self._forbid()
                raft = getattr(store, "raft", None)
                if raft is None:
                    self._err(400, "not a server-backed agent")
                    return True
                ids = [store.node_id] + list(raft.peers)
                self._send({"Servers": [
                    {"ID": i, "Node": i, "Leader": i == (raft.leader_id
                     if not raft.is_leader() else store.node_id),
                     "Voter": True} for i in ids]})
                return True
            if path == "/v1/agent/services" and verb == "GET":
                if srv.local is not None:
                    out = {sid: {"ID": sid, "Service": s["name"],
                                 "Tags": s["tags"], "Port": s["port"],
                                 "Address": s["address"], "Meta": s["meta"]}
                           for sid, s in srv.local.services().items()
                           if self.authz.service_read(s["name"])}
                else:
                    out = {s["id"]: {"ID": s["id"], "Service": s["name"],
                                     "Tags": s["tags"], "Port": s["port"],
                                     "Address": s["address"],
                                     "Meta": s["meta"]}
                           for s in store.node_services(srv.node_name)
                           if self.authz.service_read(s["name"])}
                self._send(self._filtered(q, out))
                return True
            if path == "/v1/agent/checks" and verb == "GET":
                def _chk_visible(service_id: str) -> bool:
                    # service checks filter by service:read on their
                    # service name, node checks by node:read (aclFilter)
                    if not service_id:
                        return self.authz.node_read(srv.node_name)
                    if srv.local is not None:
                        s = srv.local.services().get(service_id)
                    else:
                        s = next((x for x in
                                  store.node_services(srv.node_name)
                                  if x["id"] == service_id), None)
                    return self.authz.service_read(
                        s["name"] if s else service_id)
                if srv.local is not None:
                    out = {cid: {"CheckID": cid, "Name": c["name"],
                                 "Status": c["status"], "Output": c["output"],
                                 "ServiceID": c["service_id"],
                                 "Node": srv.node_name}
                           for cid, c in srv.local.checks().items()
                           if _chk_visible(c["service_id"])}
                else:
                    out = {c["check_id"]: _check_json(c, srv.node_name)
                           for c in store.node_checks(srv.node_name)
                           if _chk_visible(c["service_id"])}
                self._send(self._filtered(q, out))
                return True
            if path == "/v1/agent/maintenance" and verb == "PUT":
                # node maintenance mode registers/clears the reserved
                # critical check (agent.EnableNodeMaintenance,
                # agent/agent.go _node_maintenance)
                if not self.authz.node_write(srv.node_name):
                    return self._forbid()
                self._node_maintenance(
                    q.get("enable", "").lower() == "true",
                    q.get("reason") or (
                        "Maintenance mode is enabled for this node, "
                        "but no reason was provided. This is a default "
                        "message."))
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/service/([^/]+)", path)
            if m and verb == "GET" and m.group(1) not in (
                    "register", "maintenance"):
                # blocking agent-local service view with RESOLVED proxy
                # config — the endpoint `consul connect envoy`
                # bootstraps from (agent/http_register.go:43,
                # agent/agent_endpoint.go AgentService).  Blocks on
                # ?hash= like the reference (hash of the rendered
                # definition, not a raft index: agent-local state has
                # none).
                sid = m.group(1)

                def _render():
                    row = next((s for s in
                                store.node_services(srv.node_name)
                                if s["id"] == sid), None)
                    if row is None:
                        return None
                    resolved = None
                    dest = (row.get("proxy") or {}).get(
                        "destination_service") or row["name"]
                    hit = srv.cached_read("resolved_service_config",
                                          dest, self.headers, q) \
                        if row.get("kind") else None
                    if hit is not None:
                        resolved = hit[0]
                    return self._agent_service_json(sid, row, resolved)

                body0 = _render()
                if body0 is None:
                    self._err(404, f"unknown service id {sid!r}")
                    return True
                if not self.authz.service_read(body0["Service"]):
                    return self._forbid()
                if "hash" in q:
                    deadline = time.time() + min(
                        _parse_wait(q.get("wait", "300s")), 600.0)
                    while time.time() < deadline:
                        # snapshot the index BEFORE rendering so a
                        # write landing mid-render wakes the wait;
                        # wait_for(idx0) parks only while
                        # _index <= idx0 (one new write suffices)
                        idx0 = store.index
                        body0 = _render()
                        if body0 is None or \
                                body0["ContentHash"] != q["hash"]:
                            break
                        store.wait_for(idx0,
                                       timeout=deadline - time.time())
                    if body0 is None:
                        self._err(404, f"unknown service id {sid!r}")
                        return True
                self._send(body0,
                           extra_headers={"X-Consul-ContentHash":
                                          body0["ContentHash"]})
                return True
            m = re.fullmatch(r"/v1/agent/service/maintenance/(.+)", path)
            if m and verb == "PUT":
                sid = m.group(1)
                svc = srv.local.services().get(sid) \
                    if srv.local is not None else None
                if svc is None:
                    svc_row = next((s for s in
                                    store.node_services(srv.node_name)
                                    if s["id"] == sid), None)
                    name = svc_row["name"] if svc_row else None
                else:
                    name = svc["name"]
                if name is None:
                    self._err(404, f"unknown service id {sid!r}")
                    return True
                if not self.authz.service_write(name):
                    return self._forbid()
                self._service_maintenance(
                    sid, q.get("enable", "").lower() == "true",
                    q.get("reason") or (
                        "Maintenance mode is enabled for this service, "
                        "but no reason was provided. This is a default "
                        "message."))
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/token/(.+)", path)
            if m and verb == "PUT":
                # runtime agent-token update (agent/token/store.go;
                # agent_endpoint.go AgentToken requires agent:write)
                if not self.authz.agent_write(srv.node_name):
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                if not srv.tokens.set(m.group(1),
                                      body.get("Token", ""),
                                      from_api=True):
                    self._err(404, f"unknown token slot {m.group(1)!r}")
                    return True
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/join/(.+)", path)
            if m and verb == "PUT":
                # idempotent join: the sim's membership is tensor-state,
                # so joining an already-known member revives it; unknown
                # addresses have no socket to dial (agent_endpoint.go
                # AgentJoin requires agent:write)
                if not self.authz.agent_write(srv.node_name):
                    return self._forbid()
                target = m.group(1)
                try:
                    oracle.node_id(target)   # O(1), no member dump
                except KeyError:
                    self._err(500, f"join failed: no sim member "
                              f"{target!r}")
                    return True
                # unconditional: revive of an alive member is a no-op,
                # and the members snapshot may be up to 1s stale
                oracle.revive(target)
                self._send(None)
                return True
            if path == "/v1/agent/host" and verb == "GET":
                # host diagnostics (agent/debug/host.go; requires
                # operator:read like AgentHost)
                if not self.authz.operator_read():
                    return self._forbid()
                import os as _os
                import platform as _platform
                try:
                    load = _os.getloadavg()
                except OSError:  # pragma: no cover
                    load = (0.0, 0.0, 0.0)
                mem_total = mem_free = 0
                try:
                    with open("/proc/meminfo") as f:
                        for line in f:
                            if line.startswith("MemTotal:"):
                                mem_total = int(line.split()[1]) * 1024
                            elif line.startswith("MemAvailable:"):
                                mem_free = int(line.split()[1]) * 1024
                except OSError:  # pragma: no cover
                    pass
                self._send({
                    "Host": {"OS": _platform.system().lower(),
                             "Platform": _platform.platform(),
                             "Hostname": _platform.node(),
                             "KernelVersion": _platform.release()},
                    "CPU": {"Cores": _os.cpu_count() or 0,
                            "LoadAvg1": load[0], "LoadAvg5": load[1],
                            "LoadAvg15": load[2]},
                    "Memory": {"Total": mem_total,
                               "Available": mem_free},
                    "CollectionTime": int(time.time() * 1e9),
                })
                return True
            m = re.fullmatch(r"/v1/agent/health/service/name/(.+)", path)
            if m and verb == "GET":
                name = m.group(1)
                if not self.authz.service_read(name):
                    return self._forbid()
                out, worst = [], "passing"
                for s in store.node_services(srv.node_name):
                    if s["name"] != name:
                        continue
                    st = self._aggregate_service_status(s["id"])
                    worst = _worse_status(worst, st)
                    out.append({"AggregatedStatus": st,
                                "Service": {"ID": s["id"],
                                            "Service": s["name"],
                                            "Port": s["port"],
                                            "Tags": s["tags"]}})
                if not out:
                    # empty-but-200 would read as healthy to rollout
                    # gates (reference: 404 ServiceNotFound)
                    self._err(404, f"ServiceName {name!r} Not Found")
                    return True
                self._send(out, code=_health_http_code(worst))
                return True
            m = re.fullmatch(r"/v1/agent/health/service/id/(.+)", path)
            if m and verb == "GET":
                sid = m.group(1)
                svc = next((s for s in store.node_services(srv.node_name)
                            if s["id"] == sid), None)
                if svc is None:
                    self._err(404, f"unknown service id {sid!r}")
                    return True
                if not self.authz.service_read(svc["name"]):
                    return self._forbid()
                st = self._aggregate_service_status(sid)
                self._send({"AggregatedStatus": st,
                            "Service": {"ID": sid,
                                        "Service": svc["name"],
                                        "Port": svc["port"],
                                        "Tags": svc["tags"]}},
                           code=_health_http_code(st))
                return True
            if path == "/v1/agent/service/register" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                sid = body.get("ID") or body.get("Name")
                if not self.authz.service_write(body.get("Name", sid)):
                    return self._forbid()
                self._agent_register_service(sid, body)
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/service/deregister/(.+)", path)
            if m and verb == "PUT":
                sid = m.group(1)
                svc = srv.local.services().get(sid) \
                    if srv.local is not None else None
                if svc is None:
                    # store-registered (connect-proxy) services aren't in
                    # local state: resolve the NAME from the catalog so
                    # ACL checks match registration
                    svc = next((s for s in
                                store.node_services(srv.node_name)
                                if s["id"] == sid), None)
                if not self.authz.service_write(
                        svc["name"] if svc else sid):
                    return self._forbid()
                if srv.local is not None and sid in srv.local.services():
                    if srv.checks is not None:
                        for cid, c in srv.local.checks().items():
                            if c["service_id"] == sid:
                                srv.checks.remove(cid)
                    srv.local.remove_service(sid)
                    srv.local.sync_changes(store)
                else:
                    # store-registered services (connect-proxy sidecars
                    # bypass local state) deregister store-side — no
                    # ghost proxies surviving their own deregistration
                    self._drop_service_runners(sid)
                    store.deregister_service(srv.node_name, sid)
                # an auto-registered sidecar (connect.sidecar_service)
                # leaves with its parent (agent removeService cascade)
                scid = servicemgr.sidecar_id_for(sid)
                if any(s["id"] == scid
                       for s in store.node_services(srv.node_name)):
                    self._drop_service_runners(scid)
                    store.deregister_service(srv.node_name, scid)
                self._send(None)
                return True
            if path == "/v1/agent/check/register" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                sid = body.get("ServiceID", "")
                if sid:
                    svc = next((s for s in store.node_services(srv.node_name)
                                if s["id"] == sid), None)
                    ok = self.authz.service_write(svc["name"] if svc else sid)
                else:
                    ok = self.authz.node_write(srv.node_name)
                if not ok:
                    return self._forbid()
                cid = body.get("CheckID") or body.get("Name")
                self._agent_register_check(cid, body, sid)
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/check/deregister/(.+)", path)
            if m and verb == "PUT":
                if not (self.authz.node_write(srv.node_name)
                        or self._check_update_allowed(m.group(1))):
                    return self._forbid()
                if srv.checks is not None:
                    srv.checks.remove(m.group(1))
                if srv.local is not None:
                    srv.local.remove_check(m.group(1))
                    srv.local.sync_changes(store)
                else:
                    store.deregister_check(srv.node_name, m.group(1))
                self._send(None)
                return True
            m = re.fullmatch(r"/v1/agent/check/(pass|warn|fail)/(.+)", path)
            if m and verb == "PUT":
                cid = m.group(2)
                if not (self.authz.node_write(srv.node_name)
                        or self._check_update_allowed(cid)):
                    return self._forbid()
                status = {"pass": "passing", "warn": "warning",
                          "fail": "critical"}[m.group(1)]
                note = q.get("note", "")
                ttl = srv.checks.ttl(cid) if srv.checks is not None else None
                if ttl is not None:
                    ttl.set_status(status, note)   # notifies local state
                    srv.local.sync_changes(store)
                elif srv.local is not None and srv.local.update_check(
                        cid, status, note):
                    srv.local.sync_changes(store)
                else:
                    try:
                        store.update_check(srv.node_name, cid, status,
                                           output=note)
                    except KeyError:
                        self._err(404, "unknown check")
                        return True
                self._send(None)
                return True
            if path == "/v1/agent/reload" and verb == "PUT":
                # agent:write like the reference (AgentReload)
                if not self.authz.agent_write(srv.node_name):
                    return self._forbid()
                if srv.reload_fn is None:
                    self._err(400, "agent not started from config sources")
                    return True
                self._send(srv.reload_fn())
                return True
            m = re.fullmatch(r"/v1/agent/force-leave/(.+)", path)
            if m and verb == "PUT":
                # operator:write (AgentForceLeave, agent_endpoint.go:565)
                if not self.authz.operator_write():
                    return self._forbid()
                oracle.leave(m.group(1))
                self._send(None)
                return True
            if path == "/v1/agent/leave" and verb == "PUT":
                # agent:write on this node (AgentLeave, agent_endpoint.go:547)
                if not self.authz.agent_write(srv.node_name):
                    return self._forbid()
                oracle.leave(srv.node_name)
                self._send(None)
                return True
            if path == "/v1/catalog/register" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                node = body.get("Node", srv.node_name)
                if not self.authz.node_write(node):
                    return self._forbid()
                if body.get("Service") and not self.authz.service_write(
                        body["Service"].get("Service", "")):
                    return self._forbid()
                idx = store.register_node(node, body.get("Address", ""),
                                          meta=body.get("NodeMeta") or {})
                svc = body.get("Service")
                if svc:
                    idx = store.register_service(
                        node, svc.get("ID") or svc.get("Service"),
                        svc.get("Service", ""), port=svc.get("Port", 0),
                        tags=svc.get("Tags") or [],
                        address=svc.get("Address", ""))
                chk = body.get("Check")
                if chk:
                    idx = store.register_check(
                        node, chk.get("CheckID", ""), chk.get("Name", ""),
                        status=chk.get("Status", "critical"),
                        service_id=chk.get("ServiceID", ""))
                self._send(True, index=idx)
                return True
            if path == "/v1/catalog/deregister" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                node = body.get("Node")
                if not self.authz.node_write(node or ""):
                    return self._forbid()
                if body.get("ServiceID"):
                    store.deregister_service(node, body["ServiceID"])
                else:
                    store.deregister_node(node)
                self._send(True)
                return True
            if path == "/v1/catalog/datacenters" and verb == "GET":
                # WAN-distance-sorted DC list (catalog_endpoint.go
                # ListDatacenters via router.GetDatacentersByDistance;
                # cached: cache-types/catalog_datacenters.go)
                dcs, _idx, state = self._cache_or_live(
                    "catalog_datacenters", "", q,
                    lambda: (srv.router.datacenters()
                             if srv.router is not None else [srv.dc]))
                self._send(dcs,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/catalog/nodes" and verb == "GET":
                raw_nodes, idx, state = self._cache_or_live(
                    "catalog_nodes", "", q, store.nodes,
                    ("nodes", ""), view_topic="nodes")
                rows = [{"Node": n["node"], "ID": n["id"],
                         "Address": n["address"], "Meta": n["meta"],
                         "ModifyIndex": n["modify_index"]}
                        for n in raw_nodes
                        if self.authz.node_read(n["node"])]
                rows = self._filtered(q, rows)
                if "near" in q:
                    rows = self._near_sort(q["near"], rows,
                                           key=lambda r: r["Node"])
                self._send(rows, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/catalog/services" and verb == "GET":
                services, idx, state = self._cache_or_live(
                    "catalog_services", "", q, store.services,
                    ("services", ""))
                self._send({k: v for k, v in services.items()
                            if self.authz.service_read(k)}, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/catalog/service/(.+)", path)
            if m and verb == "GET":
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                rows, idx, state = self._cache_or_live(
                    "catalog_service_nodes", m.group(1), q,
                    lambda: store.service_nodes(m.group(1),
                                                tag=q.get("tag")),
                    ("services", m.group(1)), ("nodes", ""),
                    cacheable=not q.get("tag"),
                    view_topic="services", view_sub_key=m.group(1),
                    view_disc=f"tag={q.get('tag') or ''}")
                out = self._filtered(q, [_catalog_service_json(r)
                                         for r in rows])
                if "near" in q:
                    out = self._near_sort(q["near"], out,
                                          key=lambda r: r["Node"])
                self._send(out, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/catalog/gateway-services/(.+)", path)
            if m and verb == "GET":
                # services bound to a gateway via its config entry
                # (catalog_endpoint.go GatewayServices)
                gw = m.group(1)
                if not self.authz.service_read(gw):
                    return self._forbid()
                from consul_tpu import gateways as gmod
                raw, idx, state = self._cache_or_live(
                    "gateway_services", gw, q,
                    lambda: gmod.gateway_services(store, gw),
                    ("config", ""))
                rows = [r for r in raw
                        if r["Service"] == gmod.WILDCARD
                        or self.authz.service_read(r["Service"])]
                self._send(rows, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/catalog/connect/(.+)", path)
            if m and verb == "GET":
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                idx = self._block(q, ("services", ""), ("nodes", ""))
                rows = store.connect_service_nodes(m.group(1))
                self._send(self._filtered(
                    q, [_catalog_service_json(r) for r in rows]),
                    index=idx)
                return True
            m = re.fullmatch(r"/v1/catalog/node/(.+)", path)
            if m and verb == "GET":
                node = m.group(1)
                if not self.authz.node_read(node):
                    return self._forbid()  # before blocking: no stall/leak
                idx = self._block(q, ("nodes", node))
                nrec = next((n for n in store.nodes() if n["node"] == node),
                            None)
                if nrec is None:
                    self._send(None, index=idx)
                    return True
                node_svcs, _i, state = self._cache_or_live(
                    "node_services", node, q,
                    lambda: store.node_services(node))
                svcs = {s["id"]: {"ID": s["id"], "Service": s["name"],
                                  "Tags": s["tags"], "Port": s["port"],
                                  "Meta": s["meta"]}
                        for s in node_svcs
                        if self.authz.service_read(s["name"])}
                self._send({"Node": {"Node": node, "Address": nrec["address"],
                                     "Meta": nrec["meta"]},
                            "Services": svcs}, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/health/service/(.+)", path)
            if m and verb == "GET":
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                name = m.group(1)
                if ("cached" in q or self._read_mode == "stale") \
                        and srv.view_store is not None:
                    # backend choice (rpcclient/health): Cache-Control
                    # max-age rides the request-keyed agent cache; plain
                    # ?cached — and every ?stale read, the follower
                    # read plane's heavy-GET path — rides the streaming
                    # materialized view, so N clients polling one
                    # service share one Materializer + one store
                    # subscription (agent/submatview role)
                    tag = q.get("tag")
                    passing = "passing" in q
                    hit = srv.cached_read(
                        "health_services",
                        f"{name}\x00{tag or ''}\x00{passing}",
                        self.headers, q)
                    if hit is not None:
                        rows, idx, cache_state = hit
                        rows = rows or []
                        # falls through to the shared tail: ?near
                        # sorting and response conventions identical
                    else:
                        view = srv.view_store.get(
                        "health", name,
                        lambda: (store.health_service_nodes(
                            name, tag=tag, passing_only=passing),
                            store.index),
                            view_key=f"tag={tag}|passing={passing}")
                        min_idx = int(q["index"]) if "index" in q else 0
                        rows, idx = view.fetch(
                            min_idx,
                            timeout=_parse_wait(q.get("wait", "300s"))
                            if "index" in q else 0.0)
                        rows = rows or []
                        cache_state = None
                else:
                    cache_state = None
                    idx = self._block(q, ("health", name),
                                      ("services", name), ("nodes", ""))
                    rows = store.health_service_nodes(
                        name, tag=q.get("tag"),
                        passing_only="passing" in q)
                out = self._filtered(q, [_health_json(r, store)
                                         for r in rows])
                if "near" in q:
                    out = self._near_sort(q["near"], out,
                                          key=lambda r: r["Node"]["Node"])
                self._send(out, index=idx, extra_headers=(
                    {"X-Cache": cache_state} if cache_state else None))
                return True
            m = re.fullmatch(r"/v1/health/checks/(.+)", path)
            if m and verb == "GET":
                # all checks of a service's instances
                # (health_endpoint.go ServiceChecks)
                name = m.group(1)
                if not self.authz.service_read(name):
                    return self._forbid()

                def _live_checks():
                    return [c for r in store.health_service_nodes(name)
                            for c in r["checks"]
                            if c.get("service_id")]

                checks, idx, state = self._cache_or_live(
                    "health_checks", name, q, _live_checks,
                    ("health", name))
                out = [_check_json(c, c.get("node", ""))
                       for c in checks]
                self._send(self._filtered(q, out), index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/internal/ui/nodes" and verb == "GET":
                # UI summary: one row per node with check counts
                # (agent/ui_endpoint.go UINodes; cached via node_dump)
                rows, idx, state = self._cache_or_live(
                    "node_dump", "", q, srv._ui_nodes_summary,
                    ("nodes", ""), ("nodechecks", ""))
                out = [r for r in rows
                       if self.authz.node_read(r["Node"])]
                self._send(self._filtered(q, out), index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/internal/ui/services" and verb == "GET":
                # UI summary: one row per service name with instance +
                # check rollups and kind (agent/ui_endpoint.go
                # UIServices; cached via the service_dump type)
                rows, idx, state = self._cache_or_live(
                    "service_dump", "", q, srv._ui_services_summary,
                    ("services", ""), ("nodechecks", ""))
                out = [r for r in rows
                       if self.authz.service_read(r["Name"])]
                self._send(self._filtered(q, out), index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/internal/ui/cluster-metrics" \
                    and verb == "GET":
                # the federation view (consul_tpu/introspect.py): every
                # configured node's /v1/agent/metrics + raft config +
                # visibility SLIs merged into one leader/lag table —
                # the metrics-proxy-shaped sibling endpoint serving the
                # CLUSTER's own telemetry instead of an external
                # provider's.  Same ACL bar as the metrics proxy
                # (metric names can leak node/service names).
                if srv.cluster_nodes is None:
                    self._err(404, "cluster metrics are not enabled "
                                   "(no cluster_nodes configured)")
                    return True
                if not (self.authz.node_read_all()
                        and self.authz.service_read_all()):
                    return self._forbid()
                from consul_tpu import introspect
                view = introspect.cluster_view(
                    srv.cluster_nodes,
                    events_since=int(q.get("events_since", 0) or 0),
                    events_limit=int(q.get("events_limit", 50) or 0))
                self._send(view)
                return True
            if path == "/v1/internal/ui/federation" and verb == "GET":
                # the WAN view (introspect.federation_view): every
                # configured DC's cluster_view merged into one
                # DC -> leader/lag/visibility table + a dc-tagged
                # cross-DC event timeline — the multi-DC sibling of
                # cluster-metrics (the reference's UI topology +
                # metrics-proxy serve the same story per-DC).  Same
                # no-SSRF discipline: a fixed configured set only,
                # same ACL bar as the metrics proxy.
                if srv.federation_nodes is None:
                    self._err(404, "federation view is not enabled "
                                   "(no federation_nodes configured)")
                    return True
                if not (self.authz.node_read_all()
                        and self.authz.service_read_all()):
                    return self._forbid()
                from consul_tpu import introspect
                view = introspect.federation_view(
                    srv.federation_nodes,
                    events_limit=int(q.get("events_limit", 50) or 0))
                self._send(view)
                return True
            if path == "/v1/internal/ui/xds" and verb == "GET":
                # the mesh-control-plane table (ISSUE 16): per-proxy
                # rebuild/push SLIs off the proxycfg Manager.
                # ?local=1 serves THIS node's own table; without it
                # the merged fleet view scrapes the same fixed
                # configured node set as cluster-metrics (never a
                # caller-supplied URL — the no-SSRF stance), 404 when
                # unconfigured.  Same ACL bar as the metrics proxy
                # (proxy ids and service names leak topology).
                if not (self.authz.node_read_all()
                        and self.authz.service_read_all()):
                    return self._forbid()
                if q.get("local"):
                    self._send({"node": srv.node_name,
                                "proxies": srv.proxycfg.table(),
                                "shapes":
                                    srv.proxycfg.shape_stats()})
                    return True
                if srv.cluster_nodes is None:
                    self._err(404, "xds view is not enabled "
                                   "(no cluster_nodes configured)")
                    return True
                from consul_tpu import introspect
                self._send(introspect.xds_view(srv.cluster_nodes))
                return True
            if path == "/v1/internal/ui/replication" and verb == "GET":
                # per-Replicator status table (ISSUE 18): lag,
                # diverged, content hashes, rounds — the per-node
                # surface federation_view + debug_bundle scrape.
                # Readable without a token like /v1/acl/replication:
                # hashes and lag leak no payload content.
                reps = list(srv.replicators)
                if srv.acl_replicator is not None \
                        and srv.acl_replicator not in reps:
                    reps.append(srv.acl_replicator)
                rows = [r.status() for r in reps]
                ctrl = srv.limit_controller
                self._send({
                    "node": srv.node_name, "dc": srv.dc,
                    "replicators": rows,
                    "write_rate": round(ctrl.rate, 1)
                    if ctrl is not None else None})
                return True
            m = re.fullmatch(r"/v1/internal/replication/([a-z-]+)",
                             path)
            if m and verb == "GET":
                # raw store-shaped replication feed (the internal
                # replication RPCs, acl_replication.go /
                # config_replication.go): a secondary DC's replicators
                # list the primary's payload through this — reached
                # cross-DC via the ?dc= WAN forward above.  Token and
                # policy payloads carry SECRETS, so those lists demand
                # acl:write (the replication token's bar in the
                # reference); the mesh-routing lists settle for
                # operator read via node+service read.
                what = m.group(1)
                listers = {
                    "tokens": store.acl_token_list,
                    "policies": store.acl_policy_list,
                    "intentions": store.intention_list,
                    "config-entries": store.config_entry_list,
                    "federation-states": store.federation_state_list,
                }
                if what not in listers:
                    self._err(404, f"unknown replication payload "
                                   f"{what!r}")
                    return True
                if what in ("tokens", "policies"):
                    if not self.authz.acl_write():
                        return self._forbid()
                elif not (self.authz.node_read_all()
                          and self.authz.service_read_all()):
                    return self._forbid()
                self._send({"index": store.index,
                            "rows": listers[what]()})
                return True
            if path.startswith("/v1/internal/ui/metrics-proxy/") \
                    and verb == "GET":
                # reverse proxy to the configured metrics provider
                # (agent/http_register.go:98, agent/ui_endpoint.go
                # UIMetricsProxy): path under the prefix appends to
                # base_url, is normalized against traversal, and must
                # match the allowlist exactly; the caller's token never
                # leaves this agent; add_headers are injected (e.g.
                # provider auth).  Requires read on all nodes+services
                # like the reference (metrics can leak their names).
                cfg = srv.ui_metrics_proxy or {}
                if not cfg.get("base_url"):
                    self._err(404, "Metrics proxy is not enabled")
                    return True
                if not (self.authz.node_read_all()
                        and self.authz.service_read_all()):
                    return self._forbid()
                import posixpath
                # allowlist applies to the SUB-path (normalized
                # against traversal) BEFORE joining base_url, so a
                # base_url with its own path prefix
                # (http://prom:9090/prometheus) still works
                sub = posixpath.normpath(
                    path[len("/v1/internal/ui/metrics-proxy"):])
                if sub not in (cfg.get("path_allowlist") or []):
                    self._err(403, f"path {sub!r} is not in the "
                                   f"metrics proxy allowlist")
                    return True
                url = cfg["base_url"] + sub
                # rebuild the query from the RAW string so repeated
                # params (prometheus match[]=a&match[]=b) survive; the
                # caller's ACL token must not reach the provider on
                # ANY auth path (?token= included)
                raw_q = urllib.parse.urlparse(self.path).query
                pairs = [(k, v) for k, v in urllib.parse.parse_qsl(
                    raw_q, keep_blank_values=True) if k != "token"]
                qs = urllib.parse.urlencode(pairs)
                if qs:
                    url += "?" + qs
                req = urllib.request.Request(url, method="GET")
                for h in cfg.get("add_headers") or []:
                    req.add_header(h["name"], h["value"])

                class _NoRedirect(urllib.request.HTTPRedirectHandler):
                    # following a provider redirect would re-send the
                    # configured auth header to an arbitrary host
                    # OUTSIDE the allowlist (SSRF + credential
                    # forwarding); refuse instead
                    def redirect_request(self, *a, **kw):
                        return None

                opener = urllib.request.build_opener(_NoRedirect())
                cap = 4 * 1024 * 1024
                try:
                    with opener.open(req, timeout=10) as r:
                        body = r.read(cap + 1)
                        if len(body) > cap:
                            # a silently truncated 200 would hand the
                            # UI a cut-off JSON body
                            self._err(502, "metrics provider response "
                                           "exceeds the 4 MiB proxy "
                                           "cap")
                            return True
                        ctype = r.headers.get(
                            "Content-Type", "application/json")
                except urllib.error.HTTPError as e:
                    if 300 <= e.code < 400:
                        self._err(502, "metrics provider answered a "
                                       "redirect; refusing to follow")
                    else:
                        self._err(e.code,
                                  f"metrics provider: {e.reason}")
                    return True
                except (urllib.error.URLError, OSError) as e:
                    self._err(502, f"metrics provider unreachable: "
                                   f"{e}")
                    return True
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return True
            m = re.fullmatch(
                r"/v1/internal/ui/service-topology/(.+)", path)
            if m and verb == "GET":
                # upstream/downstream topology with intention
                # decisions (agent/http_register.go:104,
                # agent/ui_endpoint.go UIServiceTopology; derivation
                # catalog/store.py service_topology)
                svc = urllib.parse.unquote(m.group(1))
                if not self.authz.service_read(svc):
                    return self._forbid()
                kind = q.get("kind", "")
                if kind not in ("", "ingress-gateway"):
                    # the reference 400s other kinds
                    # (ui_endpoint.go UIServiceTopology)
                    self._err(400, f"Unsupported service kind "
                                   f"{kind!r}")
                    return True
                topo, idx, state = self._cache_or_live(
                    "service_topology", svc, q,
                    lambda: store.service_topology(
                        svc, default_allow=srv.default_allow,
                        kind=kind),
                    ("services", ""), ("intentions", ""),
                    ("nodechecks", ""), ("config", ""),
                    cacheable=(kind == ""))

                def summarize(edge):
                    # ServiceTopologySummary: health rollup + the
                    # intention decision for the edge
                    rows = store.health_service_nodes(edge["name"])
                    counts = {"passing": 0, "warning": 0,
                              "critical": 0}
                    for r in rows:
                        worst = "passing"
                        for c in r["checks"]:
                            s = c["status"]
                            if s == "critical":
                                worst = "critical"
                            elif s == "warning" and \
                                    worst != "critical":
                                worst = "warning"
                        counts[worst] += 1
                    d = edge["decision"]
                    return {
                        "Name": edge["name"],
                        "Datacenter": srv.dc,
                        "InstanceCount": len(rows),
                        "ChecksPassing": counts["passing"],
                        "ChecksWarning": counts["warning"],
                        "ChecksCritical": counts["critical"],
                        "Source": edge["source"],
                        "Intention": {
                            "Allowed": d["Allowed"],
                            "HasPermissions": d["HasPermissions"],
                            "HasExact": d["HasExact"],
                            "ExternalSource": d["ExternalSource"],
                            "DefaultAllow": srv.default_allow,
                        }}

                # one ACL check per distinct edge name (edges repeat
                # across the filters below)
                readable = {e["name"]: self.authz.service_read(
                    e["name"]) for e in (topo["upstreams"]
                                         + topo["downstreams"])}
                self._send({
                    "Protocol": topo["protocol"],
                    "TransparentProxy": topo["transparent_proxy"],
                    "Upstreams": [
                        summarize(e) for e in topo["upstreams"]
                        if readable[e["name"]]],
                    "Downstreams": [
                        summarize(e) for e in topo["downstreams"]
                        if readable[e["name"]]],
                    "FilteredByACLs": not all(readable.values()),
                }, index=idx,
                    extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(
                r"/v1/internal/intention-upstreams/(.+)", path)
            if m and verb == "GET":
                # service names `svc` may dial per intentions — what a
                # transparent proxy watches
                # (agent/cache-types/intention_upstreams.go, served by
                # Internal.IntentionUpstreams)
                svc = urllib.parse.unquote(m.group(1))
                if not self.authz.service_read(svc):
                    return self._forbid()
                names, idx, state = self._cache_or_live(
                    "intention_upstreams", svc, q,
                    lambda: [e["name"] for e in
                             store.intention_topology(
                                 svc, downstreams=False,
                                 default_allow=srv.default_allow)],
                    ("intentions", ""), ("services", ""))
                self._send([n for n in names
                            if self.authz.service_read(n)],
                           index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(
                r"/v1/internal/ui/gateway-services-nodes/(.+)", path)
            if m and verb == "GET":
                # services behind a gateway, with their health rows
                # (agent/ui_endpoint.go UIGatewayServicesNodes)
                gw = m.group(1)
                if not self.authz.service_read(gw):
                    return self._forbid()
                from consul_tpu import gateways as gmod
                idx = self._block(q, ("config", ""), ("health", ""))
                rows = gmod.resolve_wildcard(
                    store, gmod.gateway_services(store, gw))
                out = []
                seen = set()
                for row in rows:
                    svc = row["Service"]
                    if svc in seen or \
                            not self.authz.service_read(svc):
                        continue
                    seen.add(svc)
                    out += [_health_json(r, store) for r in
                            store.health_service_nodes(svc)]
                self._send(out, index=idx)
                return True
            m = re.fullmatch(r"/v1/health/connect/(.+)", path)
            if m and verb == "GET":
                # mesh-capable (sidecar) instances of the service
                # (health_endpoint.go Connect=true path)
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                rows, idx, state = self._cache_or_live(
                    "health_connect", m.group(1), q,
                    lambda: store.health_connect_nodes(
                        m.group(1), passing_only="passing" in q),
                    ("health", ""), ("nodes", ""),
                    cacheable="passing" not in q)
                self._send(self._filtered(
                    q, [_health_json(r, store) for r in rows]),
                    index=idx,
                    extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/health/ingress/(.+)", path)
            if m and verb == "GET":
                # ingress gateways exposing the service: health rows of
                # the GATEWAY instances (health_endpoint.go Ingress=true)
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                from consul_tpu import gateways as gmod
                idx = self._block(q, ("config", ""), ("health", ""))
                out, seen_gw = [], set()
                for row in gmod.ingress_gateways_for(store, m.group(1)):
                    gw_name = row["Gateway"]
                    # one health row set per gateway even when the
                    # service is bound on several of its listeners
                    if gw_name in seen_gw or \
                            not self.authz.service_read(gw_name):
                        continue
                    seen_gw.add(gw_name)
                    out += [_health_json(r, store) for r in
                            store.health_service_nodes(gw_name)]
                self._send(out, index=idx)
                return True
            m = re.fullmatch(r"/v1/health/node/(.+)", path)
            if m and verb == "GET":
                if not self.authz.node_read(m.group(1)):
                    return self._forbid()  # before blocking: no stall/leak
                idx = self._block(q, ("nodechecks", m.group(1)))
                self._send(self._filtered(q, [
                    _check_json(c, c.get("node", m.group(1)))
                    for c in store.node_checks(m.group(1))
                    if self._check_visible(m.group(1), c)]),
                           index=idx)
                return True
            m = re.fullmatch(r"/v1/health/state/(.+)", path)
            if m and verb == "GET":
                checks, idx, state = self._cache_or_live(
                    "checks_in_state", m.group(1), q,
                    lambda: store.checks_in_state(m.group(1)),
                    ("nodechecks", ""))
                svc_cache: dict = {}
                self._send(self._filtered(q, [
                    _check_json(c, c["node"]) for c in checks
                    if self.authz.node_read(c["node"])
                    and self._check_visible(c["node"], c, svc_cache)]),
                           index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/session/create" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                if not self.authz.session_write(
                        body.get("Node", srv.node_name)):
                    return self._forbid()
                ttl = _parse_wait(body["TTL"]) if body.get("TTL") else 0.0
                sid, _ = store.session_create(
                    body.get("Node", srv.node_name), ttl=ttl,
                    behavior=body.get("Behavior", "release"),
                    lock_delay=_parse_wait(str(body.get("LockDelay", "15s"))))
                self._send({"ID": sid})
                return True
            m = re.fullmatch(r"/v1/session/destroy/(.+)", path)
            if m and verb == "PUT":
                if not self._session_node_write(m.group(1)):
                    return self._forbid()
                store.session_destroy(m.group(1))
                self._send(True)
                return True
            m = re.fullmatch(r"/v1/session/renew/(.+)", path)
            if m and verb == "PUT":
                if not self._session_node_write(m.group(1)):
                    return self._forbid()
                ok = store.session_renew(m.group(1))
                if not ok:
                    self._err(404, "session not found")
                    return True
                info = store.session_info(m.group(1))
                self._send([_session_json(info)])
                return True
            m = re.fullmatch(r"/v1/session/info/(.+)", path)
            if m and verb == "GET":
                info = store.session_info(m.group(1))
                if info and not self.authz.session_read(info["node"]):
                    info = None  # filtered, not 403 (aclFilter)
                self._send([_session_json(info)] if info else [])
                return True
            if path == "/v1/session/list" and verb == "GET":
                self._send([_session_json(s) for s in store.session_list()
                            if self.authz.session_read(s["node"])])
                return True
            m = re.fullmatch(r"/v1/session/node/(.+)", path)
            if m and verb == "GET":
                self._send([_session_json(s) for s in store.session_list()
                            if s["node"] == m.group(1)
                            and self.authz.session_read(s["node"])])
                return True
            if path == "/v1/coordinate/nodes" and verb == "GET":
                out, seen = [], set()
                for mem in oracle.members():
                    if mem["status"] != "alive":
                        continue
                    if not self.authz.node_read(mem["name"]):
                        continue  # aclFilter on coordinates
                    c = oracle.coordinate(mem["name"])
                    seen.add(mem["name"])
                    out.append(_coord_json(c, srv.dc))
                # externally-pushed coordinates (PUT /v1/coordinate/
                # update) for nodes outside the sim
                for row in store.coordinate_list():
                    if row["node"] in seen or \
                            not self.authz.node_read(row["node"]):
                        continue
                    out.append({"Node": row["node"], "Segment": "",
                                "Coord": row["coord"]})
                self._send(out)
                return True
            if path == "/v1/coordinate/update" and verb == "PUT":
                # Coordinate.Update: raft-applied batch write
                # (coordinate_endpoint.go:117; batched :63-113)
                body = json.loads(self._body() or b"{}")
                node = body.get("Node", srv.node_name)
                if not self.authz.node_write(node):
                    return self._forbid()
                idx = store.coordinate_batch_update(
                    [{"node": node, "coord": body.get("Coord") or {}}])
                self._send(True, index=idx)
                return True
            if path == "/v1/coordinate/datacenters" and verb == "GET":
                dcs = srv.router.datacenters() if srv.router is not None \
                    else [srv.dc]
                self._send([{"Datacenter": d, "AreaID": "wan",
                             "Coordinates": []} for d in dcs])
                return True
            m = re.fullmatch(r"/v1/coordinate/node/(.+)", path)
            if m and verb == "GET":
                if not self.authz.node_read(m.group(1)):
                    self._send([])
                    return True
                try:
                    c = oracle.coordinate(m.group(1))
                except KeyError:
                    row = store.coordinate_get(m.group(1))
                    if row is None:
                        self._send([])
                        return True
                    self._send([{"Node": row["node"], "Segment": "",
                                 "Coord": row["coord"]}])
                    return True
                self._send([_coord_json(c, srv.dc)])
                return True
            m = re.fullmatch(r"/v1/event/fire/(.+)", path)
            if m and verb == "PUT":
                if not self.authz.event_write(m.group(1)):
                    return self._forbid()
                payload = self._body()
                eid = oracle.fire_event(m.group(1), payload,
                                        origin=srv.node_name)
                self._send({"ID": eid, "Name": m.group(1),
                            "Payload": base64.b64encode(payload).decode(),
                            "Version": 1, "LTime": 0})
                return True
            if path == "/v1/event/list" and verb == "GET":
                name = q.get("name")
                out = [{"ID": str(e["id"]), "Name": e["name"],
                        "Payload": base64.b64encode(e["payload"]).decode(),
                        "LTime": e["ltime"],
                        "Coverage": oracle.event_coverage(e["id"])}
                       for e in oracle.event_list()
                       if (name is None or e["name"] == name)
                       and self.authz.event_read(e["name"])]
                self._send(out)
                return True
            if path == "/v1/query" or path.startswith("/v1/query/"):
                return self._query(verb, path, q)
            if path.startswith("/v1/connect/") \
                    or path.startswith("/v1/agent/connect/") \
                    or path.startswith("/v1/agent/xds/"):
                return self._connect(verb, path, q)
            if path == "/v1/config" and verb == "PUT":
                # EnsureConfigEntry (config_endpoint.go Apply): writes
                # need operator:write like mesh config in the reference
                if not self.authz.operator_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                kind = (body.get("Kind") or "").lower()
                name = body.get("Name", "")
                if not name and kind == "mesh":
                    name = "mesh"     # MeshConfigEntry's implicit name
                if not name:
                    # an empty name would store an entry unreachable by
                    # the single-entry GET/DELETE routes
                    self._err(400, "config entry Name is required")
                    return True
                entry = _lower_keys({k: v for k, v in body.items()
                                     if k not in ("Kind", "Name")})
                try:
                    store.config_entry_set(kind, name, entry)
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send(True)
                return True
            m = re.fullmatch(r"/v1/config/([^/]+)/?([^/]*)", path)
            if m and verb == "GET":
                # reads gate on service:read of the entry name (the
                # reference's config entry read ACLs); lists filter
                kind = m.group(1)
                if not m.group(2):
                    entries, idx, state = self._cache_or_live(
                        "config_entries", kind, q,
                        lambda: store.config_entry_list(kind),
                        ("config", ""))
                    self._send(
                        [_config_json(e) for e in entries
                         if self.authz.service_read(e.get("name", ""))],
                        index=idx,
                        extra_headers=self._cache_headers(state))
                    return True
                idx = self._block(q, ("config", ""))
                if not self.authz.service_read(m.group(2)):
                    return self._forbid()
                e = store.config_entry_get(kind, m.group(2))
                if e is None:
                    self._err(404, "config entry not found")
                    return True
                self._send(_config_json(e), index=idx)
                return True
            m = re.fullmatch(r"/v1/config/([^/]+)/([^/]+)", path)
            if m and verb == "DELETE":
                if not self.authz.operator_write():
                    return self._forbid()
                store.config_entry_delete(m.group(1), m.group(2))
                self._send(True)
                return True
            m = re.fullmatch(r"/v1/discovery-chain/([^/]+)", path)
            if m and verb == "GET":
                if not self.authz.service_read(m.group(1)):
                    return self._forbid()
                from consul_tpu.discoverychain import compile_chain
                chain, idx, state = self._cache_or_live(
                    "discovery_chain", m.group(1), q,
                    lambda: compile_chain(store, m.group(1),
                                          dc=srv.dc),
                    ("config", ""))
                self._send({"Chain": chain}, index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/exec" and verb == "PUT":
                # initiator side of consul exec (remote_exec.go protocol
                # over KV + events); agent:write like agent mutations
                if not self.authz.agent_write(srv.node_name):
                    return self._forbid()
                from consul_tpu import remote_exec as rexec
                body = json.loads(self._body() or b"{}")
                session = rexec.fire_exec(
                    store, oracle, body.get("Command", ""),
                    origin=srv.node_name,
                    wait=float(body.get("Wait", 30.0)))
                self._send({"Session": session})
                return True
            m = re.fullmatch(r"/v1/exec/([^/]+)", path)
            if m and verb == "GET":
                if not self.authz.agent_read(srv.node_name):
                    return self._forbid()
                from consul_tpu import remote_exec as rexec
                res = rexec.collect_results(store, m.group(1))
                self._send({node: {
                    "Acked": r["acked"],
                    "Output": base64.b64encode(r["output"]).decode(),
                    "ExitCode": r["exit_code"]}
                    for node, r in res.items()})
                return True
            if path == "/v1/txn" and verb == "PUT":
                return self._txn()
            if path == "/v1/snapshot" and verb == "GET":
                # snapshot save/restore requires management in the
                # reference (snapshot_endpoint.go ACL check)
                if not self.authz.acl_write():
                    return self._forbid()
                from consul_tpu import snapshot as snapmod
                state = store.snapshot()
                self._send(None, raw=snapmod.write_archive(
                    state, index=state.get("index", 0)))
                return True
            if path == "/v1/snapshot" and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                from consul_tpu import snapshot as snapmod
                body = self._body()
                try:
                    state, _meta = snapmod.read_archive(body)
                    # dry-run into a scratch store: schema problems must
                    # surface BEFORE the live store is touched (the old
                    # half-restored-state failure mode)
                    StateStore.restore(state)
                except (snapmod.SnapshotError, Exception) as e:
                    # refuse-before-touch + surface it: a tampered or
                    # bit-flipped archive must never reach the store,
                    # and ops must see that it was rejected (the same
                    # consul.raft.recovery.* family the WAL loader
                    # bumps on disk corruption)
                    from consul_tpu import telemetry
                    telemetry.incr_counter(
                        ("raft", "recovery", "snapshot_rejected"))
                    self._err(400, f"invalid snapshot: {e}")
                    return True
                store.load_snapshot(state)
                self._send(None)
                return True
            return False

        # ------------------------------------------------------------- acl

        def _acl(self, verb: str, path: str, q) -> bool:
            """/v1/acl/* (agent/acl_endpoint.go; route table
            agent/http_register.go:4-30)."""
            import uuid as _uuid
            if path == "/v1/acl/replication" and verb == "GET":
                # replication status (ACLReplicationStatus): readable
                # without a token in the reference — operators probe
                # it to debug secondary-DC lag
                rep = srv.acl_replicator
                if rep is None:
                    self._send({"Enabled": False, "Running": False,
                                "SourceDatacenter": "",
                                "ReplicationType": "",
                                "ReplicatedIndex": 0,
                                "ReplicatedTokenIndex": 0,
                                "LastSuccess": None, "LastError": None,
                                "LastErrorMessage": None})
                    return True
                self._send(rep.status())
                return True
            if path == "/v1/acl/bootstrap" and verb == "PUT":
                accessor, secret = str(_uuid.uuid4()), str(_uuid.uuid4())
                ok, idx = store.acl_bootstrap(accessor, secret)
                if not ok:
                    self._err(403, "ACL bootstrap no longer allowed "
                              f"(reset index: {idx})")
                    return True
                srv.acl.invalidate()
                self._send({"AccessorID": accessor, "SecretID": secret,
                            "Description":
                                "Bootstrap Token (Global Management)",
                            "CreateIndex": idx, "ModifyIndex": idx},
                           index=idx)
                return True
            if path == "/v1/acl/policies" and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                self._send([_policy_json(p, with_rules=False)
                            for p in store.acl_policy_list()])
                return True
            if path == "/v1/acl/policy" and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                from consul_tpu.acl import PolicyError
                from consul_tpu.acl import parse as _parse_rules
                try:
                    _parse_rules(body.get("Rules", ""))
                except PolicyError as e:
                    self._err(400, str(e))
                    return True
                pid = body.get("ID") or str(_uuid.uuid4())
                try:
                    store.acl_policy_set(pid, body["Name"],
                                         body.get("Rules", ""),
                                         body.get("Description", ""))
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                srv.acl.invalidate()
                self._send(_policy_json(store.acl_policy_get(pid)))
                return True
            m = re.fullmatch(r"/v1/acl/policy/name/(.+)", path)
            if m and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                p = store.acl_policy_get_by_name(m.group(1))
                if p is None:
                    self._err(404, "policy not found")
                    return True
                self._send(_policy_json(p))
                return True
            m = re.fullmatch(r"/v1/acl/policy/([^/]+)", path)
            if m:
                pid = m.group(1)
                if verb == "GET":
                    if not self.authz.acl_read():
                        return self._forbid()
                    p = store.acl_policy_get(pid)
                    if p is None:
                        self._err(404, "policy not found")
                        return True
                    self._send(_policy_json(p))
                    return True
                if verb == "PUT":
                    if not self.authz.acl_write():
                        return self._forbid()
                    body = json.loads(self._body() or b"{}")
                    from consul_tpu.acl import PolicyError
                    from consul_tpu.acl import parse as _parse_rules
                    try:
                        _parse_rules(body.get("Rules", ""))
                        store.acl_policy_set(pid, body["Name"],
                                             body.get("Rules", ""),
                                             body.get("Description", ""))
                    except (PolicyError, ValueError) as e:
                        self._err(400, str(e))
                        return True
                    srv.acl.invalidate()
                    self._send(_policy_json(store.acl_policy_get(pid)))
                    return True
                if verb == "DELETE":
                    if not self.authz.acl_write():
                        return self._forbid()
                    store.acl_policy_delete(pid)
                    srv.acl.invalidate()
                    self._send(True)
                    return True
            if path == "/v1/acl/tokens" and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                self._send([_token_json(t, store, secret=False)
                            for t in store.acl_token_list()])
                return True
            if path == "/v1/acl/token" and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                accessor = body.get("AccessorID") or str(_uuid.uuid4())
                # updating an existing token must not rotate its secret or
                # demote its type (TokenSet upsert semantics)
                existing = store.acl_token_get(accessor) or {}
                secret = body.get("SecretID") or existing.get("secret") \
                    or str(_uuid.uuid4())
                policies = [p.get("ID") or p.get("Name")
                            for p in body.get("Policies", [])]
                # identity grants (structs.ACLServiceIdentity /
                # ACLNodeIdentity, agent/structs/acl.go:141,193).
                # Names are interpolated into synthetic policy HCL, so
                # they must match the reference's strict charset
                # (isValidServiceIdentityName — lowercase alnum/dash/
                # underscore only); anything looser is rule injection.
                _ident_re = re.compile(
                    r"^[a-z0-9]([a-z0-9_-]*[a-z0-9])?$")
                sids, nids = [], []
                for si in body.get("ServiceIdentities") or []:
                    name_ = (si or {}).get("ServiceName", "")
                    if not _ident_re.fullmatch(name_ or ""):
                        self._err(400, "ServiceIdentities require a "
                                       "literal lowercase ServiceName "
                                       "(alnum, dash, underscore)")
                        return True
                    sids.append({"service_name": name_,
                                 "datacenters":
                                     si.get("Datacenters") or []})
                for ni in body.get("NodeIdentities") or []:
                    name_ = (ni or {}).get("NodeName", "")
                    if not _ident_re.fullmatch(name_ or ""):
                        self._err(400, "NodeIdentities require a "
                                       "literal lowercase NodeName "
                                       "(alnum, dash, underscore)")
                        return True
                    if not ni.get("Datacenter"):
                        self._err(400, "NodeIdentities require a "
                                       "Datacenter")
                        return True
                    nids.append({"node_name": name_,
                                 "datacenter": ni["Datacenter"]})
                store.acl_token_set(accessor, secret, policies,
                                    body.get("Description", ""),
                                    token_type=existing.get("type", "client"),
                                    local=body.get("Local", False),
                                    service_identities=sids,
                                    node_identities=nids)
                srv.acl.invalidate()
                self._send(_token_json(store.acl_token_get(accessor), store))
                return True
            if path == "/v1/acl/token/self" and verb == "GET":
                t = store.acl_token_get_by_secret(self.token or "")
                if t is None:
                    self._err(403, "ACL not found")
                    return True
                self._send(_token_json(t, store))
                return True
            m = re.fullmatch(r"/v1/acl/token/([^/]+)/clone", path)
            if m and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                src = store.acl_token_get(m.group(1))
                if src is None:
                    self._err(404, "token not found")
                    return True
                accessor, secret = str(_uuid.uuid4()), str(_uuid.uuid4())
                store.acl_token_set(
                    accessor, secret, src["policies"],
                    src["description"], src["type"], src["local"],
                    service_identities=src.get("service_identities"),
                    node_identities=src.get("node_identities"))
                self._send(_token_json(store.acl_token_get(accessor), store))
                return True
            m = re.fullmatch(r"/v1/acl/token/([^/]+)", path)
            if m:
                accessor = m.group(1)
                if verb == "GET":
                    if not self.authz.acl_read():
                        return self._forbid()
                    t = store.acl_token_get(accessor)
                    if t is None:
                        self._err(404, "token not found")
                        return True
                    self._send(_token_json(t, store))
                    return True
                if verb == "DELETE":
                    if not self.authz.acl_write():
                        return self._forbid()
                    store.acl_token_delete(accessor)
                    srv.acl.invalidate()
                    self._send(True)
                    return True
            return False

        # -------------------------------------------------- prepared queries
        # /v1/query CRUD + execute + explain
        # (agent/consul/prepared_query_endpoint.go:341,477; structs
        # PreparedQuery* JSON shapes)

        def _query_defn(self, body: dict) -> dict:
            svc = body.get("Service") or {}
            fo = svc.get("Failover") or {}
            defn = {
                "name": body.get("Name", ""),
                "session": body.get("Session", ""),
                "token": body.get("Token", ""),
                "service": {
                    "service": svc.get("Service", ""),
                    "tags": svc.get("Tags") or [],
                    "only_passing": bool(svc.get("OnlyPassing")),
                    "near": svc.get("Near", ""),
                    "failover": {
                        "nearest_n": int(fo.get("NearestN") or 0),
                        "datacenters": fo.get("Datacenters") or [],
                    },
                },
                "dns": {"ttl": (body.get("DNS") or {}).get("TTL", "")},
            }
            tpl = body.get("Template")
            if tpl:
                defn["template"] = {"type": tpl.get("Type",
                                                    "name_prefix_match"),
                                    "regexp": tpl.get("Regexp", "")}
            return defn

        def _query_json(self, q_: dict) -> dict:
            svc = q_.get("service") or {}
            fo = svc.get("failover") or {}
            out = {
                "ID": q_.get("id", ""), "Name": q_.get("name", ""),
                "Session": q_.get("session", ""),
                "Token": q_.get("token", ""),
                "Service": {
                    "Service": svc.get("service", ""),
                    "Tags": svc.get("tags", []),
                    "OnlyPassing": svc.get("only_passing", False),
                    "Near": svc.get("near", ""),
                    "Failover": {
                        "NearestN": fo.get("nearest_n", 0),
                        "Datacenters": fo.get("datacenters", []),
                    },
                },
                "DNS": {"TTL": (q_.get("dns") or {}).get("ttl", "")},
                "CreateIndex": q_.get("create_index", 0),
                "ModifyIndex": q_.get("modify_index", 0),
            }
            if q_.get("template"):
                out["Template"] = {"Type": q_["template"].get("type", ""),
                                   "Regexp": q_["template"].get("regexp",
                                                                "")}
            return out

        def _query(self, verb: str, path: str, q) -> bool:
            import uuid as _uuid
            if path == "/v1/query" and verb == "PUT":  # POST routes as PUT
                body = json.loads(self._body() or b"{}")
                if not self.authz.query_write(body.get("Name", "")):
                    return self._forbid()
                defn = self._query_defn(body)
                qid = str(_uuid.uuid4())
                try:
                    store.query_set(qid, defn)
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send({"ID": qid})
                return True
            if path == "/v1/query" and verb == "GET":
                idx = self._block(q, ("queries", ""))
                self._send([self._query_json(x) for x in store.query_list()
                            if self.authz.query_read(x.get("name", ""))],
                           index=idx)
                return True
            m = re.fullmatch(r"/v1/query/([^/]+)/execute", path)
            if m and verb == "GET":
                # ?cached rides the prepared_query type
                # (cache-types/prepared_query.go); the key carries the
                # execute discriminators
                ck = "\x00".join((m.group(1),
                                  str(int(q.get("limit", 0) or 0)),
                                  q.get("near") or ""))
                res, _idx, state = self._cache_or_live(
                    "prepared_query", ck, q,
                    lambda: srv.query_executor.execute(
                        m.group(1), limit=int(q.get("limit", 0) or 0),
                        near=q.get("near")))
                if res is None:
                    self._err(404, "query not found")
                    return True
                if not self.authz.service_read(res["Service"]):
                    return self._forbid()
                nodes = [_catalog_service_json(r) for r in res["Nodes"]]
                self._send({"Service": res["Service"], "Nodes": nodes,
                            "DNS": {"TTL": res["DNS"].get("ttl", "")},
                            "Datacenter": res["Datacenter"],
                            "Failovers": res["Failovers"]},
                           extra_headers=self._cache_headers(state))
                return True
            m = re.fullmatch(r"/v1/query/([^/]+)/explain", path)
            if m and verb == "GET":
                from consul_tpu import prepared_query as pq
                resolved = pq.resolve(store, m.group(1))
                if resolved is None:
                    self._err(404, "query not found")
                    return True
                if not self.authz.query_read(resolved.get("name", "")):
                    return self._forbid()
                self._send({"Query": self._query_json(resolved)})
                return True
            m = re.fullmatch(r"/v1/query/([^/]+)", path)
            if m and verb == "GET":
                q_ = store.query_get(m.group(1))
                if q_ is None:
                    self._err(404, "query not found")
                    return True
                if not self.authz.query_read(q_.get("name", "")):
                    return self._forbid()
                self._send([self._query_json(q_)])
                return True
            if m and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                existing = store.query_get(m.group(1))
                if existing is None:
                    self._err(404, "query not found")
                    return True
                # modify needs write on BOTH the old and the new name —
                # otherwise a token could hijack queries it can't touch
                # (prepared_query_endpoint.go Apply checks both)
                if not self.authz.query_write(existing.get("name", "")) \
                        or not self.authz.query_write(body.get("Name", "")):
                    return self._forbid()
                try:
                    store.query_set(m.group(1), self._query_defn(body))
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send(True)
                return True
            if m and verb == "DELETE":
                q_ = store.query_get(m.group(1))
                if q_ is not None and not self.authz.query_write(
                        q_.get("name", "")):
                    return self._forbid()
                store.query_delete(m.group(1))
                self._send(True)
                return True
            return False

        # --------------------------------------------------------- connect
        # intentions CRUD/match/check (intention_endpoint.go:73), agent
        # authorize (AgentConnectAuthorize), CA roots/rotation + leaf
        # signing (provider.go:58, leader_connect_ca.go:53)

        def _intention_json(self, it: dict) -> dict:
            return {"ID": it.get("id", ""),
                    "SourceName": it["source"],
                    "DestinationName": it["destination"],
                    "Action": it["action"],
                    "Description": it.get("description", ""),
                    "Meta": it.get("meta", {}),
                    "Precedence": it["precedence"],
                    "CreateIndex": it.get("create_index", 0),
                    "ModifyIndex": it.get("modify_index", 0)}

        def _connect(self, verb: str, path: str, q) -> bool:
            import uuid as _uuid
            from consul_tpu.connect import intentions as imod
            if path == "/v1/connect/intentions" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                dst = body.get("DestinationName", "")
                if not self.authz.intention_write(dst):
                    return self._forbid()
                iid = str(_uuid.uuid4())
                try:
                    store.intention_set(
                        iid, body.get("SourceName", "*"), dst,
                        body.get("Action", "deny"),
                        body.get("Description", ""),
                        body.get("Meta") or {})
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send({"ID": iid})
                return True
            if path == "/v1/connect/intentions" and verb == "GET":
                rows, idx, state = self._cache_or_live(
                    "intention_list", "", q, store.intention_list,
                    ("intentions", ""))
                self._send([self._intention_json(i) for i in rows
                            if self.authz.intention_read(i["destination"])],
                           index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/connect/intentions/match" and verb == "GET":
                name = q.get("name", "")
                by = q.get("by", "destination")
                if by not in ("source", "destination"):
                    self._err(400, "by must be source|destination")
                    return True
                if not self.authz.intention_read(name):
                    return self._forbid()
                rows, idx, state = self._cache_or_live(
                    "intention_match", f"{by}\x00{name}", q,
                    lambda: imod.match_order(store.intention_list(),
                                             name, by),
                    ("intentions", ""))
                self._send({name: [self._intention_json(i) for i in rows]},
                           index=idx,
                           extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/connect/intentions/check" and verb == "GET":
                src_n = q.get("source", "")
                dst_n = q.get("destination", "")
                if not self.authz.service_read(dst_n):
                    return self._forbid()
                ok, _reason = imod.authorize(
                    store.intention_list(), src_n, dst_n,
                    srv.default_allow)
                self._send({"Allowed": ok})
                return True
            m = re.fullmatch(r"/v1/connect/intentions/([^/]+)", path)
            if m and verb == "GET":
                it = store.intention_get(m.group(1))
                if it is None:
                    self._err(404, "intention not found")
                    return True
                if not self.authz.intention_read(it["destination"]):
                    return self._forbid()
                self._send(self._intention_json(it))
                return True
            if m and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                it = store.intention_get(m.group(1))
                if it is None:
                    self._err(404, "intention not found")
                    return True
                dst = body.get("DestinationName", it["destination"])
                if not self.authz.intention_write(it["destination"]) \
                        or not self.authz.intention_write(dst):
                    return self._forbid()
                try:
                    store.intention_set(
                        m.group(1), body.get("SourceName", it["source"]),
                        dst, body.get("Action", it["action"]),
                        body.get("Description",
                                 it.get("description", "")),
                        body.get("Meta") or it.get("meta") or {})
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send(True)
                return True
            if m and verb == "DELETE":
                it = store.intention_get(m.group(1))
                if it is not None and not self.authz.intention_write(
                        it["destination"]):
                    return self._forbid()
                store.intention_delete(m.group(1))
                self._send(True)
                return True
            m = re.fullmatch(r"/v1/agent/xds/([^/]+)", path)
            if m and verb == "GET":
                # the xDS long-poll (delta.go:33 semantics over JSON/HTTP
                # — see consul_tpu/xds.py docstring for the divergence)
                state = srv.proxycfg.watch(m.group(1))
                if state is None:
                    self._err(404, "unknown proxy service id")
                    return True
                # authorize on the REGISTERED service name, not the raw
                # id (parity with the other agent service endpoints)
                if not self.authz.service_write(
                        state.svc.get("name", m.group(1))):
                    return self._forbid()
                from consul_tpu import flight
                from consul_tpu import xds as xdsmod
                min_v = int(q.get("version", 0) or 0)
                wait = _parse_wait(q.get("wait", "300s")) \
                    if "version" in q else 0.0
                snap = state.fetch(min_v, timeout=wait)
                if not state.alive() and \
                        srv.proxycfg.watch(m.group(1)) is None:
                    # terminal answer (ISSUE 19 satellite): the proxy
                    # deregistered while this long-poll was parked —
                    # fetch() returned promptly and the client gets a
                    # definitive Gone instead of waiting out the poll.
                    # (alive()=False with the proxy still registered
                    # means the state was merely REPLACED — fall
                    # through and serve; the next poll rebinds.)
                    self._err(410, "proxy deregistered")
                    return True
                if snap is None:
                    self._err(404, "proxy snapshot unavailable")
                    return True
                payload = xdsmod.snapshot_resources(snap)
                # incremental mode (?delta): cache recent payloads per
                # proxy and ship only changed/removed resources when
                # the client's version is still in the window
                # (DeltaAggregatedResources, delta.go:33)
                with srv._xds_cache_lock:
                    cache = getattr(state, "_payload_cache", None)
                    if cache is None:
                        cache = state._payload_cache = {}
                    cache[snap.version] = payload["Resources"]
                    for old in sorted(cache):
                        if len(cache) <= 8:
                            break
                        del cache[old]
                    prev = cache.get(min_v) if "delta" in q \
                        and min_v != snap.version else None
                if prev is not None:
                    delta_payload = {
                        "VersionInfo": payload["VersionInfo"],
                        "FromVersion": str(min_v),
                        "ProxyID": payload["ProxyID"],
                        "Service": payload["Service"],
                        "Kind": payload["Kind"],
                        "Delta": xdsmod.delta(prev,
                                              payload["Resources"]),
                    }
                    self._send(delta_payload)
                    if snap.version > min_v:
                        xdsmod.note_http_push_counters(delta_payload,
                                                       mode="delta")
                        flight.emit("xds.delta.pushed",
                                    labels={"proxy": snap.proxy_id,
                                            "mode": "delta",
                                            "version": snap.version,
                                            "index": snap.store_index})
                    state.note_push(snap)
                    return True
                self._send(payload)
                # after the response left the process: the HTTP flush
                # is this transport's ADS push (apply->push stage).
                # A wait-timeout return (version unchanged) is a
                # re-read, not a push: no counter.
                if snap.version > min_v:
                    xdsmod.note_http_push_counters(payload,
                                                   mode="full")
                    if "delta" in q and min_v > 0:
                        # the client ASKED for a delta but its version
                        # fell out of the window: downgraded to a full
                        # snapshot (version-gap fallback, ISSUE 19)
                        flight.emit("xds.delta.fallback",
                                    labels={"proxy": snap.proxy_id,
                                            "from": min_v,
                                            "version": snap.version})
                    flight.emit("xds.delta.pushed",
                                labels={"proxy": snap.proxy_id,
                                        "mode": "full",
                                        "version": snap.version,
                                        "index": snap.store_index})
                state.note_push(snap)
                return True
            if path == "/v1/connect/ca/roots" and verb == "GET":
                roots, _idx, state = self._cache_or_live(
                    "connect_ca_roots", "", q, srv.ca.roots)
                self._send({"ActiveRootID": next(
                    (r["ID"] for r in roots if r["Active"]), ""),
                    "TrustDomain": srv.ca.trust_domain,
                    "Roots": roots},
                    extra_headers=self._cache_headers(state))
                return True
            if path == "/v1/connect/ca/configuration":
                # CA provider config (connect_ca_endpoint.go
                # ConnectCAConfiguration*)
                if verb == "GET":
                    if not self.authz.operator_read():
                        return self._forbid()
                    self._send({"Provider": srv.ca.provider_name,
                                "Config": {
                                    "LeafCertTTL":
                                        f"{srv.ca.leaf_ttl_hours}h",
                                    "TrustDomain": srv.ca.trust_domain,
                                    "CSRMaxPerSecond":
                                        srv.ca.csr_max_per_second,
                                }})
                    return True
                if verb == "PUT":
                    if not self.authz.operator_write():
                        return self._forbid()
                    body = json.loads(self._body() or b"{}")
                    cfg = body.get("Config") or {}
                    # VALIDATE everything before mutating anything: a
                    # rejected request must not leave half the config
                    # applied (UpdateConfiguration is transactional)
                    try:
                        ttl_h = max(1, int(_parse_wait(
                            str(cfg["LeafCertTTL"])) // 3600)) \
                            if "LeafCertTTL" in cfg else None
                        csr_rate = float(cfg["CSRMaxPerSecond"]) \
                            if "CSRMaxPerSecond" in cfg else None
                    except (ValueError, TypeError) as e:
                        self._err(400, f"invalid CA config: {e}")
                        return True
                    provider = body.get("Provider")
                    if provider == "builtin":
                        provider = "consul"   # set_provider's alias
                    # a same-provider update with NEW root material is
                    # a rotation too (external root replaced)
                    switch = provider and (
                        provider != srv.ca.provider_name
                        or (cfg.get("RootCert")
                            and cfg["RootCert"]
                            != srv.ca.active.cert_pem))
                    if switch:
                        try:
                            srv.ca.set_provider(provider, cfg)
                        except (ValueError, TypeError) as e:
                            # TypeError: e.g. an encrypted PKCS8 key
                            # from the cryptography loaders
                            self._err(400, str(e))
                            return True
                        pub = getattr(store, "publisher", None)
                        if pub is not None:
                            from consul_tpu.stream.publisher import \
                                Event
                            pub.publish([Event(topic="ca", key="",
                                               index=store.index)])
                    if ttl_h is not None:
                        srv.ca.leaf_ttl_hours = ttl_h
                    if csr_rate is not None:
                        srv.ca.csr_max_per_second = csr_rate
                    self._send(True)
                    return True
            if path == "/v1/connect/ca/rotate" and verb == "PUT":
                # operator:write like CA config changes
                if not self.authz.operator_write():
                    return self._forbid()
                new_root = srv.ca.rotate()
                # rotation is a mesh-wide event: every proxy snapshot
                # must re-sign its leaf without waiting for other churn
                pub = getattr(store, "publisher", None)
                if pub is not None:
                    from consul_tpu.stream.publisher import Event
                    pub.publish([Event(topic="ca", key="",
                                       index=store.index)])
                self._send({"ActiveRootID": new_root})
                return True
            m = re.fullmatch(r"/v1/agent/connect/ca/leaf/([^/]+)", path)
            if m and verb == "GET":
                if not self.authz.service_write(m.group(1)):
                    return self._forbid()
                from consul_tpu.connect.ca import CARateLimitError
                try:
                    leaf, _idx, state = self._cache_or_live(
                        "connect_ca_leaf", m.group(1), q,
                        lambda: srv.proxycfg.get_leaf(m.group(1)))
                    self._send(leaf,
                               extra_headers=self._cache_headers(state))
                except CARateLimitError as e:
                    self._err(429, str(e))   # Too Many Requests
                return True
            if path == "/v1/agent/connect/authorize" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                target = body.get("Target", "")
                if not self.authz.service_write(target):
                    return self._forbid()
                client_uri = body.get("ClientCertURI", "")
                source = imod.spiffe_service(client_uri) or ""
                ok, reason = imod.authorize(store.intention_list(),
                                            source, target,
                                            srv.default_allow)
                self._send({"Authorized": ok, "Reason": reason})
                return True
            return False

        # -------------------------------------------------- auth methods
        # /v1/acl/auth-method*, /v1/acl/binding-rule*, /v1/acl/login,
        # /v1/acl/logout (acl_endpoint.go Login/Logout; authmethod/)

        def _authmethods(self, verb: str, path: str, q) -> bool:
            import uuid as _uuid
            from consul_tpu.acl import authmethod as am
            if path == "/v1/acl/login" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                try:
                    accessor, secret, pols = am.login(
                        store, body.get("AuthMethod", ""),
                        body.get("BearerToken", ""))
                except am.AuthError as e:
                    self._err(403, str(e))
                    return True
                self._send({"AccessorID": accessor, "SecretID": secret,
                            "Policies": [{"Name": p} for p in pols],
                            "AuthMethod": body.get("AuthMethod", "")})
                return True
            if path == "/v1/acl/oidc/auth-url" and verb == "PUT":
                # ssoauth: build the IdP authorization URL + single-use
                # state for the browser code flow (the flow's REDIRECT
                # leg runs in the user's browser against the IdP, not
                # through this agent)
                body = json.loads(self._body() or b"{}")
                method = store.auth_method_get(
                    body.get("AuthMethod", ""))
                if method is None or method.get("type") != "oidc":
                    self._err(400, "AuthMethod must name an oidc-type "
                                   "auth method")
                    return True
                cfg = method.get("config") or {}
                redirect = body.get("RedirectURI", "")
                # "AllowedRedirectURIs" snake-cases to
                # allowed_redirect_ur_is (trailing plural acronym);
                # accept both spellings rather than perturbing the
                # global CamelCase converter's round-trip behavior
                allowed = (cfg.get("allowed_redirect_uris")
                           or cfg.get("allowed_redirect_ur_is") or [])
                if redirect not in allowed:
                    self._err(400, f"unauthorized RedirectURI "
                                   f"{redirect!r}")
                    return True
                state = str(_uuid.uuid4())
                try:
                    src_ip = self.client_address[0]
                except (AttributeError, IndexError, TypeError):
                    src_ip = ""
                table_full = False
                with srv._oidc_lock:
                    # single-use states with a 10-minute shelf life;
                    # capped — this endpoint is unauthenticated, so an
                    # unbounded map is a trivial memory DoS.  One
                    # source IP holds at most 64 live states and past
                    # that evicts only its OWN oldest (a flooder can
                    # never flush another source's in-flight login;
                    # NAT'd users share a budget but keep the old
                    # evict-within-budget behavior).  A full global
                    # table answers 429 rather than evicting anyone.
                    now = time.time()
                    srv._oidc_states = {
                        k: v for k, v in srv._oidc_states.items()
                        if v["expires"] > now}
                    mine = [k for k, v in srv._oidc_states.items()
                            if v.get("src") == src_ip]
                    if len(mine) >= 64:
                        srv._oidc_states.pop(mine[0], None)
                    elif len(srv._oidc_states) >= 1024:
                        table_full = True
                    if not table_full:
                        srv._oidc_states[state] = {
                            "method": method["name"],
                            "redirect_uri": redirect,
                            "nonce": body.get("ClientNonce", ""),
                            "src": src_ip,
                            "expires": now + 600.0}
                if table_full:
                    # socket I/O stays outside the lock: a stalled
                    # client must not wedge every other login
                    self._err(429, "too many outstanding OIDC "
                                   "login states; retry later")
                    return True
                auth_ep = cfg.get("oidc_authorization_endpoint") or \
                    (cfg.get("oidc_discovery_url", "").rstrip("/")
                     + "/authorize")
                qs = urllib.parse.urlencode({
                    "response_type": "code",
                    "client_id": cfg.get("oidc_client_id", ""),
                    "redirect_uri": redirect,
                    "scope": " ".join(["openid"]
                                      + (cfg.get("oidc_scopes") or [])),
                    "state": state,
                    "nonce": body.get("ClientNonce", "")})
                self._send({"AuthURL": f"{auth_ep}?{qs}"})
                return True
            if path == "/v1/acl/oidc/callback" and verb == "PUT":
                body = json.loads(self._body() or b"{}")
                state = body.get("State", "")
                with srv._oidc_lock:
                    st = srv._oidc_states.pop(state, None)
                if st is None or st["expires"] < time.time():
                    self._err(403, "unknown or expired OIDC state")
                    return True
                if srv.oidc_token_fetcher is None:
                    self._err(503,
                              "OIDC code exchange needs egress to the "
                              "IdP token endpoint; no token fetcher is "
                              "configured on this agent")
                    return True
                method = store.auth_method_get(st["method"])
                if method is None:
                    self._err(400, "auth method removed mid-flow")
                    return True
                try:
                    id_token = srv.oidc_token_fetcher(
                        method.get("config") or {},
                        body.get("Code", ""), st["redirect_uri"])
                    accessor, secret, pols = am.login(
                        store, st["method"], id_token,
                        _code_flow=True,
                        _expected_nonce=st["nonce"])
                except am.AuthError as e:
                    self._err(403, str(e))
                    return True
                self._send({"AccessorID": accessor, "SecretID": secret,
                            "Policies": [{"Name": p} for p in pols],
                            "AuthMethod": st["method"]})
                return True
            if path == "/v1/acl/logout" and verb == "PUT":
                tok = store.acl_token_get_by_secret(self.token or "")
                if tok is None or tok.get("type") != "login":
                    self._err(403, "not a login token")
                    return True
                store.acl_token_delete(tok["accessor"])
                srv.acl.invalidate(self.token)
                self._send(True)
                return True
            if path == "/v1/acl/auth-method" and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                name = body.get("Name", "")
                if not name:
                    self._err(400, "auth method Name is required")
                    return True
                store.auth_method_set(
                    name, body.get("Type", "jwt"),
                    config=_lower_keys(body.get("Config") or {}),
                    description=body.get("Description", ""))
                self._send({"Name": name})
                return True
            if path == "/v1/acl/auth-methods" and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                self._send([_authmethod_json(e)
                            for e in store.auth_method_list()])
                return True
            m = re.fullmatch(r"/v1/acl/auth-method/([^/]+)", path)
            if m and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                e = store.auth_method_get(m.group(1))
                if e is None:
                    self._err(404, "auth method not found")
                    return True
                self._send(_authmethod_json(e))
                return True
            if m and verb == "PUT":
                # update-by-path (consul acl auth-method update): a typo'd
                # name silently creating a drifting duplicate is the
                # failure mode the 404 prevents
                if not self.authz.acl_write():
                    return self._forbid()
                if store.auth_method_get(m.group(1)) is None:
                    self._err(404, "auth method not found")
                    return True
                body = json.loads(self._body() or b"{}")
                store.auth_method_set(
                    m.group(1), body.get("Type", "jwt"),
                    config=_lower_keys(body.get("Config") or {}),
                    description=body.get("Description", ""))
                self._send({"Name": m.group(1)})
                return True
            if m and verb == "DELETE":
                if not self.authz.acl_write():
                    return self._forbid()
                store.auth_method_delete(m.group(1))
                self._send(True)
                return True
            if path == "/v1/acl/binding-rule" and verb == "PUT":
                if not self.authz.acl_write():
                    return self._forbid()
                body = json.loads(self._body() or b"{}")
                rid = body.get("ID") or str(_uuid.uuid4())
                try:
                    store.binding_rule_set(
                        rid, body.get("AuthMethod", ""),
                        selector=body.get("Selector", ""),
                        bind_type=body.get("BindType", "policy"),
                        bind_name=body.get("BindName", ""))
                except ValueError as e:
                    self._err(400, str(e))
                    return True
                self._send({"ID": rid})
                return True
            if path == "/v1/acl/binding-rules" and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                self._send([_bindingrule_json(r) for r in
                            store.binding_rule_list(q.get("authmethod"))])
                return True
            m = re.fullmatch(r"/v1/acl/binding-rule/([^/]+)", path)
            if m and verb == "GET":
                if not self.authz.acl_read():
                    return self._forbid()
                r = next((x for x in store.binding_rule_list()
                          if x["id"] == m.group(1)), None)
                if r is None:
                    self._err(404, "binding rule not found")
                    return True
                self._send(_bindingrule_json(r))
                return True
            if m and verb == "DELETE":
                if not self.authz.acl_write():
                    return self._forbid()
                store.binding_rule_delete(m.group(1))
                self._send(True)
                return True
            return False

        # ------------------------------------------------------------- kv

        def _kv(self, verb: str, key: str, q) -> bool:
            if verb == "GET":
                if "recurse" in q or "keys" in q:
                    idx = self._block(q, ("kv:prefix", key))
                else:
                    idx = self._block(q, ("kv", key))
                if "keys" in q:
                    # list permission filters rather than 403s (aclFilter
                    # semantics, agent/consul/acl_filter)
                    keys = [k for k in store.kv_keys(key,
                                                     q.get("separator", ""))
                            if self.authz.key_list(k)]
                    if not keys:
                        self._err(404, "")
                        return True
                    self._send(keys, index=idx)
                    return True
                if "recurse" in q:
                    rows = [r for r in store.kv_list(key)
                            if self.authz.key_read(r["key"])]
                else:
                    if not self.authz.key_read(key):
                        return self._forbid()
                    e = store.kv_get(key)
                    rows = [e] if e else []
                if not rows:
                    self._err(404, "")
                    return True
                if "raw" in q:
                    self._send(None, raw=rows[0]["value"], index=idx)
                    return True
                self._send([_kv_json(r) for r in rows], index=idx)
                return True
            if verb == "PUT":
                if not self.authz.key_write(key):
                    return self._forbid()
                body = self._body()
                if len(body) > srv.kv_max_value_size:
                    self._err(413, "Request body too large: value size "
                                   f"exceeds {srv.kv_max_value_size} limit")
                    return True
                ok, idx = store.kv_set(
                    key, body,
                    flags=int(q.get("flags", 0)),
                    cas=int(q["cas"]) if "cas" in q else None,
                    acquire=q.get("acquire"), release=q.get("release"))
                self._send(ok, index=idx)
                return True
            if verb == "DELETE":
                recurse = "recurse" in q
                allowed = self.authz.key_write_prefix(key) if recurse \
                    else self.authz.key_write(key)
                if not allowed:
                    return self._forbid()
                ok, idx = store.kv_delete(
                    key, recurse=recurse,
                    cas=int(q["cas"]) if "cas" in q else None)
                self._send(ok, index=idx)
                return True
            return False

        def _txn(self) -> bool:
            try:
                body = json.loads(self._body() or b"[]")
            except ValueError as e:
                self._err(400, f"invalid txn body: {e}")
                return True
            if not isinstance(body, list):
                self._err(400, "txn body must be an array of ops")
                return True
            if len(body) > srv.txn_max_ops:
                # maxTxnOps guard (agent/txn_endpoint.go:16 / :66)
                self._err(413, f"transaction contains too many operations "
                               f"({len(body)} > {srv.txn_max_ops})")
                return True
            ops = []
            try:
              for item in body:
                kv = item.get("KV")
                node = item.get("Node")
                svc = item.get("Service")
                chk = item.get("Check")
                ses = item.get("Session")
                if kv:
                    verb = kv["Verb"]
                    op = {"verb": verb, "key": kv["Key"]}
                    if "Value" in kv and kv["Value"] is not None:
                        op["value"] = base64.b64decode(kv["Value"])
                        if len(op["value"]) > srv.kv_max_value_size:
                            self._err(413, "value size exceeds "
                                           f"{srv.kv_max_value_size} limit")
                            return True
                    if "Index" in kv:
                        op["index"] = kv["Index"]
                    if "Session" in kv:
                        op["session"] = kv["Session"]
                    if "Flags" in kv:
                        op["flags"] = kv["Flags"]
                elif node:
                    n = node.get("Node") or {}
                    op = {"verb": "node-" + node["Verb"],
                          "node": n.get("Node") or node.get("NodeName"),
                          "address": n.get("Address", ""),
                          "meta": n.get("Meta")}
                    if op["verb"] in ("node-set", "node-cas"):
                        # fix the node uuid HERE (the proposer): raft
                        # replicas applying this op must not each mint
                        # their own (fsm proposer-fixed-ids rule)
                        existing = store.node_get(op["node"]) \
                            if op["node"] else None
                        op["node_id"] = n.get("ID") or (
                            existing or {}).get("id") or \
                            str(uuid.uuid4())
                    if "Index" in node:
                        op["index"] = node["Index"]
                elif svc:
                    s = svc.get("Service") or {}
                    op = {"verb": "service-" + svc["Verb"],
                          "node": svc.get("Node"),
                          "service_id": s.get("ID") or s.get("Service"),
                          "name": s.get("Service") or s.get("ID"),
                          "port": s.get("Port", 0),
                          "tags": s.get("Tags"), "meta": s.get("Meta"),
                          "address": s.get("Address", "")}
                    if "Index" in svc:
                        op["index"] = svc["Index"]
                elif chk:
                    c = chk.get("Check") or {}
                    op = {"verb": "check-" + chk["Verb"],
                          "node": c.get("Node"),
                          "check_id": c.get("CheckID") or c.get("Name"),
                          "name": c.get("Name") or c.get("CheckID"),
                          "status": c.get("Status", "critical"),
                          "service_id": c.get("ServiceID", ""),
                          "output": c.get("Output", "")}
                    if "Index" in chk:
                        op["index"] = chk["Index"]
                elif ses:
                    s = ses.get("Session") or {}
                    ttl = s.get("TTL", 0.0)
                    if isinstance(ttl, str):
                        ttl = _parse_wait(ttl)   # "30s" like /v1/session
                    op = {"verb": "session-" + ses["Verb"],
                          "node": s.get("Node", srv.node_name),
                          "ttl": float(ttl),
                          "behavior": s.get("Behavior", "release"),
                          "session": s.get("ID", "")}
                    if ses["Verb"] == "create":
                        # sid + wall clock fixed at the proposer so
                        # raft replicas apply the identical session
                        op["sid"] = s.get("ID") or str(uuid.uuid4())
                        op["now"] = time.time()
                else:
                    self._err(400, "unknown txn op type (want KV/Node/"
                                   "Service/Check/Session)")
                    return True
                # a None/empty name must not reach the store (it would
                # mint a None-keyed catalog row) — the reference's
                # txn_endpoint rejects these before building the op.
                # Scoped to the typed branches: KV verbs share the
                # "check-" namespace (e.g. check-index) and must not
                # trip these guards.
                if (node or svc or chk) and not op.get("node"):
                    self._err(400, f"txn {op['verb']} op missing "
                                   "node name")
                    return True
                if svc and not op.get("service_id"):
                    self._err(400, "txn service op missing service ID/name")
                    return True
                if chk and not op.get("check_id"):
                    self._err(400, "txn check op missing check ID/name")
                    return True
                ops.append(op)
            except (ValueError, KeyError, TypeError,
                    AttributeError) as e:
                # missing Verb/Key, bad base64, bad TTL string, non-dict
                # ops — client errors, not 500s
                self._err(400, f"malformed txn op: {e}")
                return True
            for op in ops:
                verb = op["verb"]
                if "key" in op:
                    # KV ops first: KV verbs share the "check-"
                    # namespace (check-index, check-session, check-
                    # not-exists) and must not hit the Check branch
                    need_read = verb in ("get", "get-tree",
                                         "check-index", "check-session",
                                         "check-not-exists")
                    ok = self.authz.key_read(op["key"]) if need_read \
                        else self.authz.key_write(op["key"])
                elif verb.startswith("node-"):
                    ok = self.authz.node_read(op["node"]) \
                        if verb == "node-get" \
                        else self.authz.node_write(op["node"])
                elif verb.startswith("service-"):
                    # authorize on the REGISTERED name when the row
                    # exists — the client may have supplied only the ID
                    reg = store.node_service(op["node"],
                                             op["service_id"]) \
                        if op.get("node") and op.get("service_id") else None
                    svc_name = reg["name"] if reg else op["name"]
                    if verb == "service-get":
                        ok = self.authz.service_read(svc_name)
                    else:
                        ok = self.authz.service_write(svc_name)
                        # a set that RENAMES the service needs write on
                        # the new name too, or a token scoped to the old
                        # name could register arbitrary services
                        if ok and verb in ("service-set",
                                           "service-cas") and \
                                op["name"] != svc_name:
                            ok = self.authz.service_write(op["name"])
                elif verb.startswith("check-"):
                    ok = self.authz.node_read(op["node"]) \
                        if verb == "check-get" \
                        else self.authz.node_write(op["node"])
                elif verb == "session-destroy":
                    ok = self._session_node_write(op["session"])
                elif verb.startswith("session-"):
                    ok = self.authz.session_write(op["node"])
                else:          # every op shape above is exhaustive
                    self._err(400, f"unknown txn verb {verb!r}")
                    return True
                if not ok:
                    return self._forbid()
            try:
                ok, results, idx = store.txn(ops)
            except (ValueError, KeyError, TypeError) as e:
                # bad verb, unknown node for session-create, malformed
                # field types — validation errors, not server faults
                self._err(400, f"{type(e).__name__}: {e}")
                return True
            if not ok:
                self._send({"Results": None,
                            "Errors": [{"OpIndex": len(results) - 1 if results else 0,
                                        "What": "txn op failed"}]}, code=409)
                return True
            out = []
            for op, res in zip(ops, results):
                v = op["verb"]
                if v == "get":
                    out.append({"KV": _kv_json(res) if res else None})
                elif v == "node-get":
                    out.append({"Node": res})
                elif v == "service-get":
                    out.append({"Service": res})
                elif v == "check-get":
                    out.append({"Check": res})
                elif v == "session-create":
                    out.append({"Session": {"ID": res}})
            self._send({"Results": out, "Errors": None}, index=idx)
            return True

        def _cache_or_live(self, type_name, key, q, live_fn, *watches,
                           cacheable=True, view_topic=None,
                           view_sub_key=None, view_disc=""):
            """(value, index, cache_state): the shared tail for every
            typed-cache route — cached_read's gate decides, the live
            branch blocks on `watches` like an uncached request.
            `cacheable=False` forces the live path (query variants the
            typed key doesn't discriminate, e.g. ?tag / ?passing).

            `view_topic` opts the route's ?stale reads into the SHARED
            materialized-view cache (submatview.ViewStore): N stale
            pollers of one key share one Materializer + one publisher
            subscription instead of N store scans per wakeup — the
            follower read plane's heavy-GET amortization
            (view_sub_key scopes the event subscription; None follows
            every key on the topic; `view_disc` carries any request
            discriminator the snapshot closure bakes in — e.g. ?tag —
            so differently-shaped requests never share one view)."""
            hit = srv.cached_read(type_name, key, self.headers, q) \
                if cacheable else None
            if hit is not None:
                return hit
            if view_topic is not None and srv.view_store is not None \
                    and self._read_mode == "stale":
                view = srv.view_store.get(
                    view_topic, view_sub_key,
                    lambda: (live_fn(), store.index),
                    view_key=f"t:{type_name}|k:{key}|{view_disc}")
                min_idx = int(q["index"]) if "index" in q else 0
                rows, idx = view.fetch(
                    min_idx,
                    timeout=_parse_wait(q.get("wait", "300s"))
                    if "index" in q else 0.0)
                return rows, idx, None
            idx = self._block(q, *watches) if watches else None
            return live_fn(), idx, None

        @staticmethod
        def _cache_headers(state):
            return {"X-Cache": state} if state else None

        def _near_sort(self, origin: str, rows, key):
            names = [key(r) for r in rows]
            try:
                order = oracle.sort_by_rtt(origin, names)
            except KeyError:
                return rows
            pos = {n: i for i, n in enumerate(order)}
            return sorted(rows, key=lambda r: pos.get(key(r), 1 << 30))

    return Handler


def _camel(obj):
    """snake_case → CamelCase for config entry RESPONSES, so read-then-
    write round-trips (the reference serves CamelCase JSON).  Values of
    opaque keys pass through verbatim."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            ck = "".join(p.capitalize() for p in k.split("_")) \
                if isinstance(k, str) else k
            out[ck] = v if (isinstance(k, str)
                            and k in _OPAQUE_KEYS) else _camel(v)
        return out
    if isinstance(obj, list):
        return [_camel(x) for x in obj]
    return obj


def _proxy_json(proxy: dict) -> dict:
    """Stored snake_case proxy block → the reference's CamelCase
    structs.ConnectProxyConfig wire shape.  The opaque Config map
    passes through verbatim."""
    out = {
        "DestinationServiceName": proxy.get("destination_service", ""),
        "DestinationServiceID": proxy.get("destination_service_id",
                                          ""),
        "LocalServiceAddress": proxy.get("local_service_address",
                                         "127.0.0.1"),
        "LocalServicePort": proxy.get("local_service_port", 0),
        "Config": proxy.get("config") or {},
        "Upstreams": [
            {"DestinationName": u.get("destination_name", ""),
             "LocalBindPort": u.get("local_bind_port", 0),
             "LocalBindAddress": u.get("local_bind_address",
                                       "127.0.0.1"),
             # the opaque per-upstream Config (escape hatches) must
             # round-trip: read-modify-write registration flows would
             # otherwise silently drop it
             **({"Config": u["config"]} if u.get("config") else {})}
            for u in proxy.get("upstreams") or []],
    }
    if proxy.get("mode"):
        out["Mode"] = proxy["mode"]
    if proxy.get("transparent_proxy"):
        out["TransparentProxy"] = _camel(proxy["transparent_proxy"])
    if proxy.get("expose"):
        out["Expose"] = _camel(proxy["expose"])
    if proxy.get("mesh_gateway"):
        out["MeshGateway"] = _camel(proxy["mesh_gateway"])
    return out


def _snake(name: str) -> str:
    """CamelCase → snake_case (PathPrefix → path_prefix)."""
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (not name[i - 1].isupper()
                                       or (i + 1 < len(name)
                                           and name[i + 1].islower())):
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


# keys whose VALUES are opaque user maps: their inner keys must pass
# through verbatim in both directions (proxy-defaults Config, Meta,
# auth-method claim mappings — claim names are IdP identifiers)
_OPAQUE_KEYS = {"config", "meta", "claim_mappings"}


def _lower_keys(obj):
    """Config entries arrive in the reference's CamelCase JSON; the
    store keeps snake_case (the HCL shape compile_chain reads).  Values
    of opaque keys are preserved verbatim."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            nk = _snake(k) if isinstance(k, str) else k
            out[nk] = v if nk in _OPAQUE_KEYS else _lower_keys(v)
        return out
    if isinstance(obj, list):
        return [_lower_keys(x) for x in obj]
    return obj


def _authmethod_json(e: dict) -> dict:
    """CamelCase wire shape, round-trippable through PUT."""
    return {"Name": e.get("name", ""), "Type": e.get("type", ""),
            "Description": e.get("description", ""),
            "Config": _camel(e.get("config") or {}),
            "CreateIndex": e.get("create_index", 0),
            "ModifyIndex": e.get("modify_index", 0)}


def _bindingrule_json(r: dict) -> dict:
    return {"ID": r.get("id", ""),
            "AuthMethod": r.get("auth_method", ""),
            "Selector": r.get("selector", ""),
            "BindType": r.get("bind_type", "policy"),
            "BindName": r.get("bind_name", ""),
            "CreateIndex": r.get("create_index", 0),
            "ModifyIndex": r.get("modify_index", 0)}


def _config_json(entry: dict) -> dict:
    """Stored snake_case entry → the reference's CamelCase wire shape
    (round-trippable through PUT /v1/config)."""
    out = _camel({k: v for k, v in entry.items()
                  if k not in ("kind", "name", "create_index",
                               "modify_index")})
    out["Kind"] = entry.get("kind", "")
    out["Name"] = entry.get("name", "")
    out["CreateIndex"] = entry.get("create_index", 0)
    out["ModifyIndex"] = entry.get("modify_index", 0)
    return out


_STATUS_RANK = {"passing": 0, "warning": 1, "critical": 2,
                "maintenance": 3}


def _worse_status(a: str, b: str) -> str:
    return a if _STATUS_RANK.get(a, 0) >= _STATUS_RANK.get(b, 0) else b


def _health_http_code(status: str) -> int:
    """AgentHealthService* response codes (agent_endpoint.go): passing
    200, warning 429, critical/maintenance 503."""
    return {"passing": 200, "warning": 429}.get(status, 503)


def _check_defn(body: dict) -> dict:
    """Normalize a structs.CheckType JSON body into CheckManager's
    lowercase definition dict (duration strings → seconds)."""
    defn = {}
    if body.get("TTL"):
        defn["ttl"] = _parse_wait(str(body["TTL"]))
    if body.get("HTTP"):
        defn["http"] = body["HTTP"]
        defn["method"] = body.get("Method", "GET")
        defn["header"] = {k: (v[0] if isinstance(v, list) else v)
                          for k, v in (body.get("Header") or {}).items()}
        defn["tls_skip_verify"] = bool(body.get("TLSSkipVerify"))
    if body.get("TCP"):
        defn["tcp"] = body["TCP"]
    if body.get("Args") or body.get("ScriptArgs"):
        defn["args"] = body.get("Args") or body.get("ScriptArgs")
    if body.get("H2PING"):
        defn["h2ping"] = body["H2PING"]
    if body.get("GRPC"):
        defn["grpc"] = body["GRPC"]
    if body.get("DockerContainerID"):
        defn["docker_container_id"] = body["DockerContainerID"]
        defn["shell_args"] = body.get("Args") or ["true"]
    if body.get("AliasNode") or body.get("AliasService"):
        defn["alias_node"] = body.get("AliasNode", "")
        defn["alias_service"] = body.get("AliasService", "")
    if body.get("Interval"):
        defn["interval"] = _parse_wait(str(body["Interval"]))
    if body.get("Timeout"):
        defn["timeout"] = _parse_wait(str(body["Timeout"]))
    return defn


# ------------------------------------------------------------ JSON shapers

def _policy_json(p: dict, with_rules: bool = True) -> dict:
    out = {"ID": p["id"], "Name": p["name"],
           "Description": p["description"],
           "CreateIndex": p["create_index"],
           "ModifyIndex": p["modify_index"]}
    if with_rules:
        out["Rules"] = p["rules"]
    return out


def _token_json(t: dict, store, secret: bool = True) -> dict:
    policies = []
    for pid in t["policies"]:
        p = store.acl_policy_get(pid) or store.acl_policy_get_by_name(pid)
        policies.append({"ID": p["id"] if p else pid,
                         "Name": p["name"] if p else pid})
    out = {"AccessorID": t["accessor"], "Description": t["description"],
           "Policies": policies, "Local": t["local"],
           "Type": t["type"],
           "CreateIndex": t["create_index"], "ModifyIndex": t["modify_index"]}
    sids = t.get("service_identities") or []
    if sids:
        out["ServiceIdentities"] = [
            dict({"ServiceName": s["service_name"]},
                 **({"Datacenters": s["datacenters"]}
                    if s.get("datacenters") else {}))
            for s in sids]
    nids = t.get("node_identities") or []
    if nids:
        out["NodeIdentities"] = [{"NodeName": n["node_name"],
                                  "Datacenter": n["datacenter"]}
                                 for n in nids]
    if secret:
        out["SecretID"] = t["secret"]
    return out


def _member_json(m: dict) -> dict:
    status_code = {"alive": 1, "leaving": 2, "left": 3, "failed": 4}
    tags = {"role": "node", "incarnation": str(m["incarnation"])}
    if "segment" in m:
        tags["segment"] = m["segment"]   # serf segment tag
    # addr_ns (segment index) namespaces the synthetic address: per-
    # pool ids restart at 0, so segmented members would otherwise
    # collide on Addr:Port.  Unsegmented pools keep the full 24-bit id
    # space; segmented pools get 256 segments x 64k nodes of unique
    # addresses (beyond that the NAME remains the identity).
    if "addr_ns" in m:
        octet2 = m["addr_ns"] & 255
    else:
        octet2 = (m["id"] >> 16) & 255
    return {"Name": m["name"],
            "Addr": f"10.{octet2}."
            f"{(m['id'] >> 8) & 255}.{m['id'] & 255}",
            "Port": 8301, "Status": status_code.get(m["status"], 0),
            "Tags": tags}


def _kv_json(e: dict) -> dict:
    return {"Key": e["key"], "Flags": e["flags"],
            "Value": base64.b64encode(e["value"]).decode(),
            "CreateIndex": e["create_index"], "ModifyIndex": e["modify_index"],
            "LockIndex": e.get("lock_index", 0),
            **({"Session": e["session"]} if e.get("session") else {})}


def _catalog_service_json(r: dict) -> dict:
    out = {"Node": r["node"], "Address": r["address"],
           "ServiceID": r["service_id"], "ServiceName": r["service_name"],
           "ServiceTags": r["tags"], "ServicePort": r["port"],
           "ServiceAddress": r["service_address"],
           "ModifyIndex": r["modify_index"]}
    # mesh rows carry their kind + proxy config (structs.ServiceNode
    # ServiceKind/ServiceProxy) — /v1/catalog/connect is useless without
    # the proxy's destination
    if r.get("kind"):
        proxy = r.get("proxy") or {}
        out["ServiceKind"] = r["kind"]
        out["ServiceProxy"] = {
            "DestinationServiceName": proxy.get(
                "destination_service", ""),
            "Upstreams": [
                {"DestinationName": u.get("destination_name", ""),
                 "LocalBindPort": u.get("local_bind_port", 0)}
                for u in proxy.get("upstreams") or []],
        }
    return out


def _check_json(c: dict, node: str) -> dict:
    return {"Node": node, "CheckID": c["check_id"], "Name": c["name"],
            "Status": c["status"], "Output": c["output"],
            "ServiceID": c["service_id"]}


def _health_json(r: dict, store: StateStore) -> dict:
    svc = r["service"]
    return {"Node": {"Node": svc["node"], "Address": svc["address"]},
            "Service": {"ID": svc["service_id"], "Service": svc["service_name"],
                        "Tags": svc["tags"], "Port": svc["port"],
                        "Address": svc["service_address"]},
            "Checks": [_check_json(c, svc["node"]) for c in r["checks"]]}


def _session_json(s: dict) -> dict:
    return {"ID": s["id"], "Node": s["node"], "Behavior": s["behavior"],
            "TTL": f"{s['ttl']}s" if s["ttl"] else "",
            "LockDelay": s["lock_delay"], "Checks": s["checks"],
            "CreateIndex": s["create_index"]}


def _coord_json(c: dict, dc: str) -> dict:
    return {"Node": c["node"], "Segment": "",
            "Coord": {"Vec": c["vec"], "Error": c["error"],
                      "Adjustment": c["adjustment"], "Height": c["height"]}}
