"""Gossip-plane payload encryption: the memberlist SecretKey role.

The reference encrypts every gossip packet with AES-GCM keyed from the
serf keyring (memberlist security.go; agent/keyring.go loads/persists
the keys; `consul keyring` rotates them).  Rotation is three-phase:
install the new key everywhere (decrypt-only), `use` it (becomes the
encrypt key), remove the old one — at every instant each node can
decrypt traffic encrypted under ANY installed key.

Here the network gossip surface is the delegate socket
(consul_tpu/delegate.py) — external agents delegating their gossip
plane to the device pool — plus the user-event payloads that ride it.
`GossipCodec` implements the same keyring semantics over AES-GCM:
encrypt under the primary key, decrypt by trying every installed key.

Frame format (one line on the delegate socket):

    ENC:<base64(version(1) | nonce(12) | ciphertext+tag)>

Version 0 is AES-GCM.  Keys are 16/24/32 raw bytes, carried base64
(the `consul keygen` shape).
"""

from __future__ import annotations

import base64
import os
from typing import List, Optional

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

_VERSION = 0
PREFIX = b"ENC:"


class DecryptError(Exception):
    """No installed key decrypts this frame (memberlist's
    'no installed keys could decrypt the message')."""


def _decode_key(key_b64: str) -> bytes:
    raw = base64.b64decode(key_b64)
    if len(raw) not in (16, 24, 32):
        raise ValueError(
            f"gossip key must be 16/24/32 bytes, got {len(raw)}")
    return raw


class GossipCodec:
    """Encrypt-with-primary / decrypt-with-any over a live keyring.

    `keyring_fn() -> (primary_b64 | None, [installed_b64...])` reads
    the CURRENT keys per call, so `keyring use`/`install`/`remove`
    take effect on the next frame with no restart (keyring.go)."""

    def __init__(self, keyring_fn):
        self.keyring_fn = keyring_fn

    @property
    def enabled(self) -> bool:
        primary, _ = self.keyring_fn()
        return primary is not None

    def encrypt_line(self, line: bytes) -> bytes:
        primary, _ = self.keyring_fn()
        if primary is None:
            return line
        key = _decode_key(primary)
        nonce = os.urandom(12)
        blob = bytes([_VERSION]) + nonce + AESGCM(key).encrypt(
            nonce, line, None)
        return PREFIX + base64.b64encode(blob)

    def decrypt_line(self, line: bytes) -> bytes:
        """Inverse of encrypt_line.  With encryption enabled a
        plaintext line is REJECTED (memberlist drops unencrypted
        packets when a keyring is loaded); with it disabled an ENC:
        frame is rejected too (we couldn't read it)."""
        primary, installed = self.keyring_fn()
        if not line.startswith(PREFIX):
            if primary is not None:
                raise DecryptError(
                    "plaintext frame rejected: gossip encryption is "
                    "enabled")
            return line
        if primary is None:
            raise DecryptError(
                "encrypted frame but no gossip keys installed")
        try:
            blob = base64.b64decode(line[len(PREFIX):])
        except ValueError:
            raise DecryptError("malformed encrypted frame")
        if len(blob) < 1 + 12 + 16 or blob[0] != _VERSION:
            raise DecryptError("malformed encrypted frame")
        nonce, ct = blob[1:13], blob[13:]
        for key_b64 in installed:
            try:
                return AESGCM(_decode_key(key_b64)).decrypt(
                    nonce, ct, None)
            except (InvalidTag, ValueError):
                continue
        raise DecryptError("no installed keys could decrypt the frame")


def oracle_keyring_fn(oracle):
    """Adapter: any oracle exposing keyring_list() → (primary,
    installed).  Works for GossipOracle AND SegmentedOracle (whose
    keys live in per-segment pools) — the generic surface is the
    listing, not private attrs."""

    def fn():
        keys = oracle.keyring_list()
        primary = next(iter(keys.get("PrimaryKeys") or {}), None)
        return primary, list(keys.get("Keys") or {})
    return fn
