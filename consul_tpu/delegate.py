"""Gossip delegate socket: `-gossip-backend=tpu-sim` for external agents.

SURVEY §5.8/§7.6's build target: a bridge exposing the memberlist
Transport/Delegate-shaped interface so an agent written in ANY language
(the reference's Go agent included) can delegate its gossip plane to
the device-resident pool instead of running its own SWIM sockets.

The protocol is deliberately language-neutral — newline-delimited JSON
over TCP, one request/response pair per line:

  {"id": 1, "method": "members", "params": {"limit": 100}}\n
  {"id": 1, "result": [...]}\n

Surface (the Delegate/Transport method set, memberlist delegate.go +
serf's event/coordinate extensions):

  node_meta        → agent tags (Delegate.NodeMeta)
  members          → member list w/ statuses (memberlist.Members)
  status           → one member's status
  join             → join a NEW node into the pool (Memberlist.Join;
                     oracle.spawn) or revive a known one
  leave            → graceful leave (Serf.Leave)
  notify_msg       → user message in (Delegate.NotifyMsg → user event)
  get_broadcasts   → user events out (Delegate.GetBroadcasts: the
                     host-side event ring since a cursor)
  local_state      → membership summary (Delegate.LocalState push/pull)
  coordinate       → Vivaldi coordinate (serf.GetCoordinate)
  rtt              → coordinate distance between two members
  ping             → liveness/round-trip of the bridge itself

Fault-injection methods (kill) are NOT exposed here: a delegate client
is an agent, not the test harness.

Latency note: the first join/leave at a given pool shape pays the XLA
compile of the rejoin computation (~tens of seconds on a tunneled
chip).  `start()` therefore precompiles the mutating kernels via
`oracle.warmup()` BEFORE accepting connections, so no client request
ever eats a compile; pass `start(warmup=False)` to skip (tests with
tiny pools).
"""

from __future__ import annotations

import base64
import json
import socket

from consul_tpu.utils.net import shutdown_and_close
import threading
from typing import Optional, Tuple


class DelegateServer:
    def __init__(self, oracle, node_meta: Optional[dict] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.oracle = oracle
        self.node_meta = node_meta or {"backend": "tpu-sim"}
        # gossip-plane encryption (memberlist SecretKey role): when the
        # oracle's keyring holds keys, every frame on this socket must
        # be AES-GCM encrypted; rotation via `keyring install/use/
        # remove` takes effect per-frame (consul_tpu/gossip_crypto.py)
        from consul_tpu.gossip_crypto import (
            GossipCodec, oracle_keyring_fn,
        )
        if hasattr(oracle, "keyring_list"):
            self.codec = GossipCodec(oracle_keyring_fn(oracle))
        else:
            self.codec = GossipCodec(lambda: (None, []))
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(16)
        self.host, self.port = self._lsock.getsockname()
        self._running = False
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: list = []
        self._conns: list = []
        self._conn_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def start(self, warmup: bool = True) -> None:
        # Precompile the mutating kernels BEFORE accepting: a client's
        # first join/leave must not eat the XLA compile inside its own
        # request timeout (memberlist-shaped consumers use ~seconds).
        if warmup and hasattr(self.oracle, "warmup"):
            self.oracle.warmup()
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        shutdown_and_close(self._lsock)
        # close LIVE connections too: a stopped server must not keep
        # answering parked clients (and their recv()s must unblock)
        with self._conn_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        for t in self._conn_threads:
            t.join(timeout=2.0)

    # ------------------------------------------------------------- serving

    def _accept(self) -> None:
        while self._running:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns = [c for c in self._conns
                               if c.fileno() >= 0] + [conn]
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._conn_threads = [x for x in self._conn_threads
                                  if x.is_alive()] + [t]

    def _serve_conn(self, conn: socket.socket) -> None:
        from consul_tpu.gossip_crypto import DecryptError
        buf = b""
        try:
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        plain = self.codec.decrypt_line(line)
                    except DecryptError:
                        # wrong/missing key: drop the CONNECTION, not
                        # just the frame — memberlist treats such a
                        # peer as outside the cluster
                        return
                    out = self._handle_line(plain)
                    try:
                        frame = self.codec.encrypt_line(out)
                    except ValueError:
                        # malformed primary key mid-rotation: a
                        # controlled drop, not a thread traceback
                        return
                    conn.sendall(frame + b"\n")
        except OSError:
            pass
        finally:
            conn.close()

    def _handle_line(self, line: bytes) -> bytes:
        try:
            req = json.loads(line)
            rid = req.get("id")
            result = self._dispatch(req.get("method", ""),
                                    req.get("params") or {})
            return json.dumps({"id": rid, "result": result}).encode()
        except Exception as e:
            rid = None
            try:
                rid = json.loads(line).get("id")
            except Exception:
                pass
            return json.dumps({"id": rid,
                               "error": f"{type(e).__name__}: {e}"
                               }).encode()

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, method: str, p: dict):
        o = self.oracle
        if method == "ping":
            return {"tick": int(o.tick)}
        if method == "node_meta":
            return self.node_meta
        if method == "members":
            kwargs = {"limit": p.get("limit"),
                      "offset": p.get("offset", 0)}
            if p.get("segment") is not None and \
                    hasattr(o, "segments"):
                kwargs["segment"] = p["segment"]
            return [{"Name": m["name"], "Status": m["status"],
                     "Incarnation": m["incarnation"]}
                    for m in o.members(**kwargs)]
        if method == "status":
            return {"Name": p["name"], "Status": o.status(p["name"])}
        if method == "join":
            name = p.get("name", "")
            try:
                o.node_id(name)
            except KeyError:
                if hasattr(o, "spawn"):
                    return {"Joined": o.spawn(name or None)}
                raise
            o.revive(name)
            return {"Joined": name}
        if method == "leave":
            o.leave(p["name"])
            return True
        if method == "notify_msg":
            payload = base64.b64decode(p.get("payload_b64", ""))
            origin = p.get("origin", "")
            try:
                o.node_id(origin)
            except KeyError:
                # an external agent isn't a pool member: inject the
                # event through the first provisioned member (the
                # bridge node plays the reference agent's role of
                # originating the serf broadcast)
                first = o.members(limit=1)
                if not first:
                    raise ValueError("empty pool: no origin for event")
                origin = first[0]["name"]
            eid = o.fire_event(p.get("name", "msg"), payload,
                               origin=origin)
            return {"ID": str(eid)}
        if method == "get_broadcasts":
            since = int(p.get("since", 0))
            out = []
            for e in o.event_list():
                if int(e["id"]) <= since:
                    continue
                out.append({"ID": int(e["id"]), "Name": e["name"],
                            "PayloadB64": base64.b64encode(
                                e["payload"]).decode(),
                            "LTime": e["ltime"]})
            return out
        if method == "local_state":
            return o.members_summary()
        if method == "coordinate":
            return o.coordinate(p["name"])
        if method == "rtt":
            return {"Seconds": o.rtt(p["a"], p["b"])}
        raise ValueError(f"unknown method {method!r}")
