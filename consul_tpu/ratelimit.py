"""Overload defense plane: ingress rate limiting + apply admission.

Production traffic means overload, and before this module nothing shed
load: every request was admitted, the leader's apply path queued
without bound, and an overloaded cluster failed by TIMING OUT — the
worst possible failure mode, because a timed-out write is AMBIGUOUS
(it may have committed; Jepsen's :info outcome) and ambiguity is
expensive everywhere downstream: clients must treat the op as
maybe-applied, the Wing & Gong checker must explore both worlds, and
operators cannot tell saturation from partition.

The reference treats overload defense as a first-class subsystem
(`agent/consul/rate` RequestLimitsHandler: token-bucket global write/
read limits with a `permissive`/`enforcing`/`disabled` mode switch;
`agent/consul/server.go`'s rpcHoldTimeout + RPCMaxBurst machinery).
Two mechanisms here, same stance:

  RateLimiter    per-client / per-route-class token buckets consulted
                 by BOTH HTTP fronts (api/http.py `_route`,
                 api/fastfront.py hot path) and the server RPC apply
                 handlers.  Over-limit requests get a FAST 429 with a
                 `Retry-After` hint and `X-Consul-Reason:
                 rate-limited` — a definite non-write, shed in
                 microseconds instead of timed out in seconds.  The
                 mode switch lets operators observe (`permissive`
                 counts + journals but admits) before they enforce.

  ApplyGate      bounded-queue + deadline admission in front of the
                 leader's `apply`/`apply_batch` (server.py).  Both
                 checks run STRICTLY BEFORE the raft log append, so a
                 rejection is a proof of non-commitment: the entry was
                 never proposed, the write CANNOT exist anywhere.
                 That turns leader overload from timeout ambiguity
                 into an unambiguous NACK
                 (`consul.raft.apply.rejected{reason}`), which the
                 Wing & Gong checker counts as a definite non-write —
                 shrinking the ambiguous-op set under chaos
                 (tests/test_overload.py asserts the shrink).

Metrics: `consul.ratelimit.{allowed,rejected}{route_class,mode}`,
`consul.raft.apply.rejected{reason}`, `consul.raft.apply.pending`
gauge.  Flight events `ratelimit.rejected` / `raft.apply.rejected`
are emission-throttled (at most one per second per class) so a
rejection storm cannot wash the flight ring of the faults that
caused it.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional, Tuple

from consul_tpu import locks, telemetry

MODES = ("disabled", "permissive", "enforcing")

# route families subject to ingress limiting: the replicated data
# plane.  /v1/agent, /v1/status, /v1/operator, /v1/internal stay
# EXEMPT by design — during the overload the limiter exists for, the
# observability surfaces (metrics federation, flight events, raft
# config) must keep answering or the operator is blind exactly when
# they need to see (the reference likewise scopes its limits to
# data-plane RPCs, not the operator surface).
_LIMITED_PREFIXES = (
    "/v1/kv/", "/v1/catalog/", "/v1/health/", "/v1/session/",
    "/v1/txn", "/v1/event/", "/v1/query", "/v1/coordinate/",
)

# flight-ring protection: at most one rejected-event journal entry per
# class per this many seconds
_EVENT_THROTTLE_S = 1.0

# bounded client table: the limiter must not become its own memory
# leak under a rotating-client attack
_MAX_CLIENTS = 4096


def route_class(verb: str, path: str) -> Optional[str]:
    """The bounded {route_class} label for one request, or None when
    the route is exempt from ingress limiting (operator surface)."""
    if not path.startswith(_LIMITED_PREFIXES):
        return None
    return "read" if verb == "GET" else "write"


class RateLimitedError(Exception):
    """Rejected by the ingress limiter — a fast, definite 429."""

    def __init__(self, rc: str, retry_after: float):
        super().__init__(
            f"rate limit exceeded for {rc} requests; retry after "
            f"{retry_after:.2f}s")
        self.route_class = rc
        self.retry_after = retry_after


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.last = now


class RateLimiter:
    """Token-bucket limiter: one global bucket per route class plus
    one per (client, route class), where `client` is the request's ACL
    token when present, else its peer address.  A request is admitted
    only when BOTH buckets have a token (the reference's global limit
    + per-caller fairness split).  Thread-safe; `disabled` mode costs
    one attribute read on the hot path."""

    def __init__(self, mode: str = "disabled",
                 read_rate: float = 500.0, read_burst: float = 1000.0,
                 write_rate: float = 200.0, write_burst: float = 400.0):
        self._lock = locks.make_lock("ratelimit.limiter")
        self.configure(mode=mode, read_rate=read_rate,
                       read_burst=read_burst, write_rate=write_rate,
                       write_burst=write_burst)
        locks.register_guards(self, self._lock,
                              "_global", "_clients", "_last_event")

    def configure(self, mode: Optional[str] = None,
                  read_rate: Optional[float] = None,
                  read_burst: Optional[float] = None,
                  write_rate: Optional[float] = None,
                  write_burst: Optional[float] = None) -> None:
        """Reconfigure live (the operator's observe-then-enforce
        workflow: start permissive, watch the rejected counters, flip
        to enforcing).  Buckets reset so new burst sizes take effect
        immediately."""
        with self._lock:
            if mode is not None:
                if mode not in MODES:
                    raise ValueError(f"mode {mode!r} not one of {MODES}")
                self.mode = mode
            prev_r = getattr(self, "_read", (500.0, 1000.0))
            prev_w = getattr(self, "_write", (200.0, 400.0))
            if read_rate is not None or read_burst is not None:
                r = float(read_rate) if read_rate is not None \
                    else prev_r[0]
                self._read = (r, float(read_burst)
                              if read_burst is not None else r * 2)
            else:
                self._read = prev_r
            if write_rate is not None or write_burst is not None:
                w = float(write_rate) if write_rate is not None \
                    else prev_w[0]
                self._write = (w, float(write_burst)
                               if write_burst is not None else w * 2)
            else:
                self._write = prev_w
            now = time.monotonic()
            # guarded-by: _lock
            self._global: Dict[str, _Bucket] = {
                "read": _Bucket(self._read[1], now),
                "write": _Bucket(self._write[1], now)}
            # (client, class) -> bucket; bounded, LRU-ish eviction
            # guarded-by: _lock
            self._clients: Dict[Tuple[str, str], _Bucket] = {}
            # guarded-by: _lock
            self._last_event: Dict[str, float] = {}

    # ------------------------------------------------------------- checking

    def _params(self, rc: str) -> Tuple[float, float]:
        return self._read if rc == "read" else self._write

    @staticmethod
    def _take(b: _Bucket, rate: float, burst: float,
              now: float) -> Optional[float]:
        """Refill + take one token; None on success, else seconds
        until a token exists (the Retry-After hint).  Elapsed time is
        clamped non-negative: callers may mix clock bases (tests pin
        `now`), and a negative elapse must never DRAIN the bucket."""
        b.tokens = min(burst, b.tokens + max(0.0, now - b.last) * rate)
        b.last = now
        if b.tokens >= 1.0:
            b.tokens -= 1.0
            return None
        return (1.0 - b.tokens) / rate if rate > 0 else 1.0

    def check(self, client: str, rc: str,
              now: Optional[float] = None) -> Optional[float]:
        """Admit one request for `client` on route class `rc`.

        Returns None when admitted; else the Retry-After hint in
        seconds — in `enforcing` mode the caller must shed (429), in
        `permissive` mode the over-limit request was counted and
        journaled but None is returned (admitted)."""
        mode = self.mode
        if mode == "disabled":
            return None
        rate, burst = self._params(rc)
        now = time.monotonic() if now is None else now
        with self._lock:
            wait_g = self._take(self._global[rc], rate, burst, now)
            # per-client fairness bucket: a single hot client exhausts
            # its own allowance (half the global rate) before it can
            # starve the global bucket for everyone
            ckey = (client, rc)
            cb = self._clients.get(ckey)
            if cb is None:
                if len(self._clients) >= _MAX_CLIENTS:
                    # evict the stalest entry: bounded memory beats
                    # perfect fairness under client churn
                    oldest = min(self._clients,
                                 key=lambda k: self._clients[k].last)
                    del self._clients[oldest]
                cb = self._clients[ckey] = _Bucket(burst, now)
            wait_c = self._take(cb, rate, burst, now)
            wait = wait_g if wait_c is None else wait_c \
                if wait_g is None else max(wait_g, wait_c)
            journal = False
            if wait is not None:
                last = self._last_event.get(rc)
                if last is None or now - last >= _EVENT_THROTTLE_S:
                    self._last_event[rc] = now
                    journal = True
        labels = {"route_class": rc, "mode": mode}
        if wait is None:
            telemetry.incr_counter(("ratelimit", "allowed"),
                                   labels=labels)
            return None
        telemetry.incr_counter(("ratelimit", "rejected"), labels=labels)
        if journal:
            from consul_tpu import flight
            flight.emit("ratelimit.rejected",
                        labels={"route_class": rc, "mode": mode})
        if mode == "permissive":
            return None
        return wait


# ---------------------------------------------------------------------------
# apply-path admission control
# ---------------------------------------------------------------------------


class ApplyRejectedError(Exception):
    """The leader NACKed an apply BEFORE appending it to the raft log:
    the write was never proposed and therefore definitely did not —
    and never will — commit.  `reason` is `queue_full` (the pending
    apply queue is at its bound) or `deadline` (the caller's shipped
    RPC budget cannot cover even the floor of a commit wait, so
    admitting it could only produce an ambiguous timeout).

    The whole point of this error is its non-ambiguity: api/client.py
    maps it to a definite failure (ambiguous=False), and the Wing &
    Gong checker treats it as a definite non-write."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(
            f"apply rejected reason={reason}"
            + (f" {detail}" if detail else ""))
        self.reason = reason

    @classmethod
    def from_rpc(cls, message: str) -> Optional["ApplyRejectedError"]:
        """Reconstruct from the RPC error string
        (`"ApplyRejectedError: apply rejected reason=<r> ..."`) so a
        forwarded NACK stays a NACK on the follower that forwarded —
        re-wrapping it as a generic RpcError would launder the
        definite failure back into ambiguity."""
        marker = "apply rejected reason="
        at = message.find(marker)
        if at < 0:
            return None
        reason = message[at + len(marker):].split()[0].strip()
        return cls(reason or "queue_full")


class ApplyGate:
    """Bounded-queue + deadline admission for the leader apply path.

    `max_pending` bounds the number of proposed-but-unapplied raft
    entries (the leader's in-flight apply queue — RaftNode._pending);
    `min_budget_s` is the commit-wait floor below which admitting a
    write can only end in an ambiguous timeout.  A commit-latency EMA
    (fed by the apply handlers' observed waits) tightens the deadline
    check under sustained load: when recent commits take longer than
    the caller's whole remaining budget, NACK now rather than time
    out later."""

    def __init__(self, max_pending: int = 4096,
                 min_budget_s: float = 0.05, enabled: bool = True):
        self.max_pending = int(max_pending)
        self.min_budget_s = float(min_budget_s)
        self.enabled = enabled
        self._ema_commit_s = 0.0    # guarded-by: _lock
        self._last_event = 0.0      # guarded-by: _lock
        self._lock = locks.make_lock("ratelimit.applygate")
        locks.register_guards(self, self._lock,
                              "_ema_commit_s", "_last_event")

    def observe_commit(self, seconds: float) -> None:
        """Feed one observed commit wait into the deadline EMA."""
        with self._lock:
            e = self._ema_commit_s
            self._ema_commit_s = seconds if e == 0.0 \
                else 0.9 * e + 0.1 * seconds

    def reject_reason(self, pending: int, n_items: int,
                      budget_s: float) -> Optional[str]:
        if not self.enabled:
            return None
        if pending + n_items > self.max_pending:
            return "queue_full"
        if budget_s <= self.min_budget_s:
            return "deadline"
        with self._lock:
            ema = min(self._ema_commit_s, 2.0)
        # the EMA influence is deliberately conservative (half the
        # recent commit latency, capped): a single slow commit must
        # not flip the gate into rejecting everything
        if ema > 0.0 and budget_s < 0.5 * ema:
            return "deadline"
        return None

    def admit(self, pending: int, n_items: int,
              budget_s: float) -> None:
        """Raise ApplyRejectedError (and count/journal it) when this
        batch must be shed; otherwise record the pending gauge.
        Runs on RPC handler / HTTP request threads — never the raft
        tick thread — so direct emission is safe."""
        reason = self.reject_reason(pending, n_items, budget_s)
        telemetry.set_gauge(("raft", "apply", "pending"),
                            float(pending))
        if reason is None:
            return
        telemetry.incr_counter(("raft", "apply", "rejected"),
                               labels={"reason": reason})
        now = time.monotonic()
        with self._lock:
            journal = now - self._last_event >= _EVENT_THROTTLE_S
            if journal:
                self._last_event = now
        if journal:
            from consul_tpu import flight
            flight.emit("raft.apply.rejected",
                        labels={"reason": reason, "pending": pending})
        raise ApplyRejectedError(
            reason, detail=f"pending={pending} n={n_items} "
                           f"budget={budget_s:.3f}s")


# ---------------------------------------------------------------------------
# self-sizing write limits (ISSUE 18)
# ---------------------------------------------------------------------------


class DynamicLimitController:
    """AIMD walk of the ingress `write_rate` against the observed
    apply latency: the reference sizes write limits from measured
    apply cost rather than a hand-set constant (`agent/consul/rate`
    + the leader's apply telemetry).  Additive increase probes for
    headroom only after `hysteresis` consecutive healthy ticks (the
    anti-oscillation guard); multiplicative decrease backs off the
    moment the ApplyGate's commit EMA or the visibility p99 crosses
    its high-water mark.  `step()` is PURE given its inputs so the
    convergence/no-oscillation dynamics unit-test without a cluster
    (tests/test_overload.py); the thread loop just samples the live
    gate + visibility and applies the decision."""

    def __init__(self, limiter: RateLimiter, apply_gate: ApplyGate,
                 vis_p99_fn=None,
                 floor: float = 20.0, ceiling: float = 2000.0,
                 increase: float = 10.0, decrease_factor: float = 0.5,
                 ema_high_s: float = 0.25, vis_high_ms: float = 2000.0,
                 hysteresis: int = 3, interval: float = 1.0):
        self.limiter = limiter
        self.apply_gate = apply_gate
        self.vis_p99_fn = vis_p99_fn
        self.floor = float(floor)
        self.ceiling = float(ceiling)
        self.increase = float(increase)
        self.decrease_factor = float(decrease_factor)
        self.ema_high_s = float(ema_high_s)
        self.vis_high_ms = float(vis_high_ms)
        self.hysteresis = int(hysteresis)
        self.interval = float(interval)
        self.rate = float(limiter._write[0])
        self.healthy_streak = 0
        self.adjustments = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        telemetry.set_gauge(("ratelimit", "rate"), self.rate)

    # ------------------------------------------------------------------ pure

    def step(self, ema_s: float, p99_ms: Optional[float] = None
             ) -> Optional[str]:
        """One control tick: returns `decrease`/`increase`/None.
        AIMD with hysteresis — decrease is immediate and
        multiplicative (halve toward the floor), increase is additive
        and only after `hysteresis` consecutive healthy ticks, so the
        walk converges to a sawtooth under sustained load instead of
        oscillating rail to rail."""
        overloaded = ema_s > self.ema_high_s or (
            p99_ms is not None and p99_ms > self.vis_high_ms)
        if overloaded:
            self.healthy_streak = 0
            new = max(self.floor, self.rate * self.decrease_factor)
            if new < self.rate:
                self._apply(new, "decrease",
                            "ema" if ema_s > self.ema_high_s
                            else "visibility")
                return "decrease"
            return None
        self.healthy_streak += 1
        if self.healthy_streak >= self.hysteresis:
            self.healthy_streak = 0
            new = min(self.ceiling, self.rate + self.increase)
            if new > self.rate:
                self._apply(new, "increase", "healthy")
                return "increase"
        return None

    def _apply(self, new_rate: float, direction: str,
               reason: str) -> None:
        self.rate = new_rate
        self.adjustments += 1
        # burst tracks rate at the limiter's default 2× ratio so a
        # shrunken rate also shrinks the burst headroom
        self.limiter.configure(write_rate=new_rate,
                               write_burst=new_rate * 2)
        telemetry.set_gauge(("ratelimit", "rate"), new_rate)
        telemetry.incr_counter(("ratelimit", "adjust"),
                               labels={"direction": direction})
        from consul_tpu import flight
        flight.emit("ratelimit.adjusted",
                    labels={"direction": direction,
                            "rate": int(new_rate), "reason": reason})

    # ------------------------------------------------------------------ live

    def tick(self) -> Optional[str]:
        """Sample the live gate + visibility plane and step once."""
        with self.apply_gate._lock:
            ema = self.apply_gate._ema_commit_s
        p99 = self.vis_p99_fn() if self.vis_p99_fn is not None else None
        return self.step(ema, p99)

    def start(self) -> None:
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:
                    pass  # a failed sample must not kill the controller
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def retry_after_header(wait_s: float) -> str:
    """Retry-After is whole seconds on the wire (RFC 9110); always at
    least 1 so a client honoring it actually backs off."""
    return str(max(1, math.ceil(wait_s)))


def parse_limit_spec(spec: str) -> dict:
    """"mode=enforcing,write_rate=50,write_burst=100,
    apply_max_pending=512" → kwargs split between RateLimiter.configure
    and the ApplyGate (tools/server_proc.py --rate-limit; env
    CONSUL_TPU_RATE_LIMIT)."""
    out: dict = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        if k == "mode":
            out["mode"] = v.strip()
        elif k in ("read_rate", "read_burst", "write_rate",
                   "write_burst", "apply_min_budget"):
            out[k] = float(v)
        elif k in ("apply_max_pending",):
            out[k] = int(v)
        elif k == "dynamic":
            # self-sizing write limits (DynamicLimitController):
            # dynamic=1 arms the AIMD controller; the *_floor/_ceiling/
            # _interval keys bound its walk
            out[k] = bool(int(v))
        elif k in ("dynamic_floor", "dynamic_ceiling",
                   "dynamic_interval"):
            out[k] = float(v)
        else:
            raise ValueError(f"unknown rate-limit key {k!r}")
    return out
