"""Logging: leveled named loggers + ring buffer + live monitor streams.

The reference uses hclog with named interceptable loggers
(logging/names.go, logger.go), optional file sinks, and live log
streaming over /v1/agent/monitor (logging/monitor/monitor.go: a monitor
registers a sink, streams buffered+new lines to the client, drops the
sink on disconnect).  Same shape: a process-wide LogBuffer holds the
recent ring and fans new lines out to monitor subscriptions; `Logger`
instances stamp level/name and feed it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, List, Optional

LEVELS = {"TRACE": 0, "DEBUG": 1, "INFO": 2, "WARN": 3, "ERROR": 4}


def level_of(line: str) -> int:
    """Parse the [LEVEL] tag of a formatted line (INFO when absent)."""
    for name, lv in LEVELS.items():
        if f"[{name}]" in line:
            return lv
    return 2


class LogBuffer:
    """Ring of recent lines + monitor fan-out (monitor/monitor.go)."""

    def __init__(self, ring: int = 512):
        self._lock = threading.Lock()
        self._ring: Deque[str] = deque(maxlen=ring)
        self._monitors: List["Monitor"] = []

    def write(self, line: str) -> None:
        with self._lock:
            self._ring.append(line)
            monitors = list(self._monitors)
        for m in monitors:
            m._push(line)

    def recent(self, n: int = 512) -> List[str]:
        with self._lock:
            return list(self._ring)[-n:]

    def monitor(self, level: str = "INFO") -> "Monitor":
        m = Monitor(self, LEVELS.get(level.upper(), 2))
        with self._lock:
            self._monitors.append(m)
        return m

    def _drop(self, m: "Monitor") -> None:
        with self._lock:
            if m in self._monitors:
                self._monitors.remove(m)


class Monitor:
    """One /v1/agent/monitor subscription: blocking line reader."""

    def __init__(self, buf: LogBuffer, min_level: int):
        self._buf = buf
        self._min_level = min_level
        self._cond = threading.Condition()
        self._queue: Deque[str] = deque()
        self._closed = False

    def _push(self, line: str) -> None:
        if level_of(line) < self._min_level:
            return
        with self._cond:
            self._queue.append(line)
            self._cond.notify_all()

    def lines(self, timeout: float = 1.0) -> List[str]:
        """Drain available lines, waiting up to `timeout` for the first."""
        with self._cond:
            if not self._queue and not self._closed:
                self._cond.wait(timeout)
            out = list(self._queue)
            self._queue.clear()
            return out

    def stop(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._buf._drop(self)


class Logger:
    """Named leveled logger (hclog shape: `ts [LEVEL] name: msg`)."""

    def __init__(self, name: str, buffer: Optional[LogBuffer] = None,
                 level: str = "INFO",
                 also: Optional[Callable[[str], None]] = None):
        self.name = name
        self.buffer = buffer if buffer is not None else default_buffer()
        self.level = LEVELS.get(level.upper(), 2)
        self.also = also

    def named(self, suffix: str) -> "Logger":
        return Logger(f"{self.name}.{suffix}", self.buffer)

    def set_level(self, level: str) -> None:
        self.level = LEVELS.get(level.upper(), 2)

    def _log(self, level: str, msg: str, **kv) -> None:
        if LEVELS[level] < self.level:
            return
        ts = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        extra = "".join(f" {k}={v}" for k, v in kv.items())
        line = f"{ts} [{level}] {self.name}: {msg}{extra}"
        self.buffer.write(line)
        if self.also is not None:
            self.also(line)

    def trace(self, msg, **kv):
        self._log("TRACE", msg, **kv)

    def debug(self, msg, **kv):
        self._log("DEBUG", msg, **kv)

    def info(self, msg, **kv):
        self._log("INFO", msg, **kv)

    def warn(self, msg, **kv):
        self._log("WARN", msg, **kv)

    def error(self, msg, **kv):
        self._log("ERROR", msg, **kv)


_default_buffer = LogBuffer()


def default_buffer() -> LogBuffer:
    return _default_buffer
