from consul_tpu.parallel import mesh

__all__ = ["mesh"]
