"""Device mesh + sharding for the simulator state.

The reference scales membership across machines with gossip fanout
(SURVEY.md §2.2); the TPU build scales the *simulation* across chips by
sharding the node axis of every [N] / [N, U] tensor over a 1-D
`jax.sharding.Mesh` ("nodes" axis).  Cross-shard interactions — gossip
scatter targets and per-subject scatter/gathers — are expressed as plain
jnp scatters under `jit` with sharding annotations, so GSPMD inserts the
ICI collectives (all-to-all-ish scatter traffic) instead of hand-written
NCCL-style point-to-point code (reference equivalent: memberlist UDP
transport, agent/consul/server_serf.go:124-131).

Multi-slice (DCN) scaling maps the WAN pool: one LAN shard group per
slice, with the WAN tensor replicated — see consul_tpu/models/wan.py.
"""

from __future__ import annotations

import contextlib
import os
import re
from typing import Iterable, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
DC_AXIS = "dc"


def _clear_backends() -> None:
    try:
        import jax.extend.backend as _jeb
        _jeb.clear_backends()
    except (ImportError, AttributeError):
        jax.clear_backends()  # older JAX spelling


def _backends_initialized():
    """Best-effort: has this process already created an XLA client?
    (XLA parses --xla_force_host_platform_device_count only at first
    client creation, so device-count inflation is only reliable before
    that point.)  None = unknown on future jax internals."""
    try:
        from jax._src import xla_bridge as _xb
        return bool(_xb._backends)
    except Exception:   # pragma: no cover - jax internals moved
        return None


@contextlib.contextmanager
def cpu_devices(n: int):
    """Expose >= n simulated CPU devices, SAVING AND RESTORING the
    global platform/flags config on exit so an in-process caller (a
    pytest module, the multichip smoke) never clobbers other tests.

    Pins the platform to cpu BEFORE any device query: the ambient env
    may register a (possibly broken / version-skewed) TPU backend, and
    without the pin array creation would materialize there.  When the
    current client already carries >= n CPU devices (the test rig's
    conftest forces 8) nothing else is mutated at all.  Otherwise the
    device count is inflated via jax_num_cpu_devices (newer jax; works
    after clear_backends) or XLA_FLAGS (older jax; only parsed at the
    FIRST client creation — if a backend already exists and the knob is
    absent, this raises with guidance rather than silently running
    single-device).  On exit the prior config/env is restored and any
    freshly-created inflated client dropped; arrays created inside the
    context live on that client — don't let them escape."""
    prev_platforms = jax.config.jax_platforms
    prev_flags = os.environ.get("XLA_FLAGS")
    knob = "jax_num_cpu_devices"
    try:
        prev_ndev = getattr(jax.config, knob)
    except AttributeError:
        prev_ndev = None
    initialized = _backends_initialized()
    mutated_env = mutated_client = False
    # when no client exists yet, the FIRST device query below creates
    # one under our mutated (cpu-pinned, possibly inflated) config —
    # that client is ours to drop on restore even when only the env
    # route was used
    created_client = initialized is False

    def restore():
        jax.config.update("jax_platforms", prev_platforms)
        if mutated_env:
            if prev_flags is None:
                os.environ.pop("XLA_FLAGS", None)
            else:
                os.environ["XLA_FLAGS"] = prev_flags
        if mutated_client and prev_ndev is not None:
            jax.config.update(knob, prev_ndev)
        if mutated_client or created_client:
            # drop the client created under the mutated config so the
            # restored config takes effect at the next backend init
            _clear_backends()

    # the setup itself mutates global state, so a setup FAILURE (rig
    # can't grow to n devices) must restore too — not only the yield
    try:
        jax.config.update("jax_platforms", "cpu")
        if initialized is False:
            # no client yet: the env route is still live — set it
            # before the first device query below creates the client
            flags = prev_flags or ""
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={n}"
                ).strip()
                mutated_env = True
        if len(jax.devices("cpu")) < n:
            try:
                _clear_backends()
                jax.config.update(knob, n)
                mutated_client = True
            except AttributeError:
                raise RuntimeError(
                    f"need {n} cpu devices, have "
                    f"{len(jax.devices('cpu'))}, and this jax lacks "
                    f"{knob} while a backend is already initialized — "
                    f"relaunch with XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n}")
        devs = jax.devices("cpu")
        if len(devs) < n:
            raise RuntimeError(
                f"need {n} cpu devices, have {len(devs)} — set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    except BaseException:
        restore()
        raise
    try:
        yield devs[:n]
    finally:
        restore()


def assert_node_sharded(leaf, n_devices: int, what: str = "state") -> None:
    """Fail loudly when a node-axis leaf is NOT spread across all
    `n_devices` — the 'knowledge matrix stays sharded' acceptance
    assert, usable on any scan output."""
    devset = getattr(getattr(leaf, "sharding", None), "device_set", set())
    if len(devset) != n_devices:
        raise AssertionError(
            f"{what} not sharded: on {len(devset)} device(s), "
            f"expected {n_devices}")


# an all-gather INSTRUCTION and its result shape(s), e.g.
#   %all-gather.3 = f32[32768,32]{1,0} all-gather(...)
#   %ag = (s8[128]{0}, s8[128]{0}) all-gather(...)
# — only the defining line, never fusions that merely consume one
_AG_RE = re.compile(r"=\s*(\([^)]*\)|[^\s(]+)\s+all-gather(?:-start)?\(")
_SHAPE_RE = re.compile(r"\[([0-9,]*)\]")


def full_gather_ops(hlo_text: str, n_nodes: int) -> List[str]:
    """All-gather instructions in a compiled module whose RESULT
    materializes a full node-axis buffer (some DIMENSION >= n_nodes —
    a replicated [N], [N, U], or doubled [2N] buffer) — the 'no
    accidental all-gather of the [N] / [N, U] buffers' audit.
    Collectives over the replicated [U]-sized rumor/[U, U] map tables
    pass regardless of element count (they ARE the cross-shard rumor
    traffic); materializing the node axis on every device does not."""
    bad = []
    for line in hlo_text.splitlines():
        m = _AG_RE.search(line)
        if m is None:
            continue
        for dims in _SHAPE_RE.findall(m.group(1)):
            if any(int(d) >= max(n_nodes, 2)
                   for d in dims.split(",") if d):
                bad.append(line.strip())
                break
    return bad


def make_mesh(devices: Iterable[jax.Device] | None = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(devs, (NODE_AXIS,))


def make_wan_mesh(devices: Iterable[jax.Device] | None = None,
                  n_dcs: int = 2) -> Mesh:
    """2-D mesh for the federation model: the vmapped per-DC batch axis
    shards over `dc` (the multi-slice/DCN analogue) and each DC's node
    axis over `nodes` (intra-slice ICI) — the dp x tp layout of this
    framework's scaling story (SURVEY §2.2 cross-DC sharding)."""
    import numpy as _np
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) % n_dcs != 0:
        raise ValueError(f"{len(devs)} devices not divisible by "
                         f"{n_dcs} dc shards")
    grid = _np.array(devs).reshape(n_dcs, len(devs) // n_dcs)
    return Mesh(grid, (DC_AXIS, NODE_AXIS))


def wan_state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a WanState: LAN leaves are [D, N, ...]
    (dc-batched, node-sharded); WAN-pool leaves are [S, ...] sharded on
    nodes; tiny tables replicate.

    The small per-DC tables ([D], [D, E], [D, U], the bridge ring) are
    REPLICATED, not dc-sharded: sharding them saves nothing (a few
    bytes per device) and the event-bridge's sequential per-dc reads
    (`wan._bridge_events`) then stay device-local — GSPMD lowers
    scalar-index slices of a sharded batch axis to mask+all-reduce
    partial sums, which the replicated layout sidesteps entirely."""
    n_dc = mesh.shape[DC_AXIS]
    n_node = mesh.shape[NODE_AXIS]

    def lan_spec(leaf):
        if leaf.ndim >= 2 and leaf.shape[0] == n_dc \
                and _node_shardable(leaf.shape[1], n_node):
            return NamedSharding(mesh, P(DC_AXIS, NODE_AXIS))
        return NamedSharding(mesh, P())

    def wan_spec(leaf):
        if leaf.ndim >= 1 and _node_shardable(leaf.shape[0], n_node):
            return NamedSharding(mesh, P(NODE_AXIS))
        return NamedSharding(mesh, P())

    return type(state)(
        lan=jax.tree_util.tree_map(lan_spec, state.lan),
        wan=jax.tree_util.tree_map(wan_spec, state.wan),
        bridged=NamedSharding(mesh, P()),
        bridged_ptr=NamedSharding(mesh, P()),
    )


def _node_shardable(dim: int, n_shards: int) -> bool:
    """One predicate for 'this axis is the node axis': divisible AND
    large relative to the shard count — slot/event tables (U, E ~ 8-32)
    must replicate, not collect all-gathers, even when divisible."""
    return dim % n_shards == 0 and dim >= 4 * n_shards


def state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a SwimState: node-leading arrays sharded on
    the node axis, rumor table + scalars replicated."""
    n_shards = mesh.shape[NODE_AXIS]

    def spec(leaf):
        if leaf.ndim >= 1 and _node_shardable(leaf.shape[0], n_shards):
            return NamedSharding(mesh, P(NODE_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec, state)


def shard_state(state, mesh: Mesh):
    """Place a SwimState onto the mesh, node axis sharded."""
    return jax.device_put(state, state_sharding(state, mesh))
