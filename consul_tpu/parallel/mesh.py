"""Device mesh + sharding for the simulator state.

The reference scales membership across machines with gossip fanout
(SURVEY.md §2.2); the TPU build scales the *simulation* across chips by
sharding the node axis of every [N] / [N, U] tensor over a 1-D
`jax.sharding.Mesh` ("nodes" axis).  Cross-shard interactions — gossip
scatter targets and per-subject scatter/gathers — are expressed as plain
jnp scatters under `jit` with sharding annotations, so GSPMD inserts the
ICI collectives (all-to-all-ish scatter traffic) instead of hand-written
NCCL-style point-to-point code (reference equivalent: memberlist UDP
transport, agent/consul/server_serf.go:124-131).

Multi-slice (DCN) scaling maps the WAN pool: one LAN shard group per
slice, with the WAN tensor replicated — see consul_tpu/models/wan.py.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
DC_AXIS = "dc"


def make_mesh(devices: Iterable[jax.Device] | None = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(devs, (NODE_AXIS,))


def make_wan_mesh(devices: Iterable[jax.Device] | None = None,
                  n_dcs: int = 2) -> Mesh:
    """2-D mesh for the federation model: the vmapped per-DC batch axis
    shards over `dc` (the multi-slice/DCN analogue) and each DC's node
    axis over `nodes` (intra-slice ICI) — the dp x tp layout of this
    framework's scaling story (SURVEY §2.2 cross-DC sharding)."""
    import numpy as _np
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) % n_dcs != 0:
        raise ValueError(f"{len(devs)} devices not divisible by "
                         f"{n_dcs} dc shards")
    grid = _np.array(devs).reshape(n_dcs, len(devs) // n_dcs)
    return Mesh(grid, (DC_AXIS, NODE_AXIS))


def wan_state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a WanState: LAN leaves are [D, N, ...]
    (dc-batched, node-sharded); WAN-pool leaves are [S, ...] sharded on
    nodes; tiny tables replicate."""
    n_dc = mesh.shape[DC_AXIS]
    n_node = mesh.shape[NODE_AXIS]

    def lan_spec(leaf):
        if leaf.ndim >= 2 and leaf.shape[0] == n_dc \
                and _node_shardable(leaf.shape[1], n_node):
            return NamedSharding(mesh, P(DC_AXIS, NODE_AXIS))
        if leaf.ndim >= 1 and leaf.shape[0] == n_dc:
            return NamedSharding(mesh, P(DC_AXIS))
        return NamedSharding(mesh, P())

    def wan_spec(leaf):
        if leaf.ndim >= 1 and _node_shardable(leaf.shape[0], n_node):
            return NamedSharding(mesh, P(NODE_AXIS))
        return NamedSharding(mesh, P())

    return type(state)(
        lan=jax.tree_util.tree_map(lan_spec, state.lan),
        wan=jax.tree_util.tree_map(wan_spec, state.wan),
        bridged=NamedSharding(mesh, P(DC_AXIS)),
        bridged_ptr=NamedSharding(mesh, P(DC_AXIS)),
    )


def _node_shardable(dim: int, n_shards: int) -> bool:
    """One predicate for 'this axis is the node axis': divisible AND
    large relative to the shard count — slot/event tables (U, E ~ 8-32)
    must replicate, not collect all-gathers, even when divisible."""
    return dim % n_shards == 0 and dim >= 4 * n_shards


def state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a SwimState: node-leading arrays sharded on
    the node axis, rumor table + scalars replicated."""
    n_shards = mesh.shape[NODE_AXIS]

    def spec(leaf):
        if leaf.ndim >= 1 and _node_shardable(leaf.shape[0], n_shards):
            return NamedSharding(mesh, P(NODE_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec, state)


def shard_state(state, mesh: Mesh):
    """Place a SwimState onto the mesh, node axis sharded."""
    return jax.device_put(state, state_sharding(state, mesh))
