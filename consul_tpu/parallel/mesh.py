"""Device mesh + sharding for the simulator state.

The reference scales membership across machines with gossip fanout
(SURVEY.md §2.2); the TPU build scales the *simulation* across chips by
sharding the node axis of every [N] / [N, U] tensor over a 1-D
`jax.sharding.Mesh` ("nodes" axis).  Cross-shard interactions — gossip
scatter targets and per-subject scatter/gathers — are expressed as plain
jnp scatters under `jit` with sharding annotations, so GSPMD inserts the
ICI collectives (all-to-all-ish scatter traffic) instead of hand-written
NCCL-style point-to-point code (reference equivalent: memberlist UDP
transport, agent/consul/server_serf.go:124-131).

Multi-slice (DCN) scaling maps the WAN pool: one LAN shard group per
slice, with the WAN tensor replicated — see consul_tpu/models/wan.py.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(devices: Iterable[jax.Device] | None = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(devs, (NODE_AXIS,))


def state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a SwimState: node-leading arrays sharded on
    the node axis, rumor table + scalars replicated."""
    n_shards = mesh.shape[NODE_AXIS]

    def spec(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] % n_shards == 0 and leaf.shape[0] > n_shards:
            return NamedSharding(mesh, P(NODE_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(spec, state)


def shard_state(state, mesh: Mesh):
    """Place a SwimState onto the mesh, node axis sharded."""
    return jax.device_put(state, state_sharding(state, mesh))
