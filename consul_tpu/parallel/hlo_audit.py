"""Compiled-program contracts: the HLO audit framework (ISSUE 20).

PR 6 proved the sharded lowering gather-free and log2-collective by
hand, then left those proofs scattered as ad-hoc audit blocks in
bench.py, tools/profile_swim.py, tools/scale_sweep.py and
tests/test_sharding.py.  This module is the ONE implementation of each
of those rules, plus a registry of every production jit entry point so
a new entry (the DNS front, a fused scan) cannot silently regress to
an all-gather with nothing failing until a chip run.

Rules (each falsifiability-tested in tests/test_hlo_lint.py):

  * gather-freedom   — zero node-axis all-gathers in the compiled
                       module (`meshlib.full_gather_ops`, promoted
                       from the PR 6 audit blocks);
  * collective census — per-family instruction counts within the
                       committed budget, no family the budget never
                       recorded (an unexpected all-reduce is a lowering
                       regression even when gather-freedom holds);
  * donation honored — `donate_argnums` must show up as
                       `input_output_alias` entries in the compiled
                       executable, not just be requested (the
                       silent-copy failure mode: XLA warns once and
                       double-buffers every [N]-shaped carry);
  * dtype-width ledger — bytes per node slot across the state pytree
                       must not widen past the committed number (the
                       PR 2 narrowing, now checked on the program's
                       actual avals rather than source text);
  * flops / peak-bytes budget — XLA's own cost model within
                       ±tolerance of the committed baseline,
                       topology-stamped like BENCH_BASELINE with the
                       same refuse-to-judge on topology mismatch;
  * compile-count    — each entry compiles exactly once per topology
                       (two dispatch-cache entries mean something
                       perturbed the static config mid-run);
  * permute scaling  — ring traffic lowers to log2(devices) static
                       collective-permutes per rotation (ops/rolls.py),
                       so permutes/log2(d) must stay flat across
                       topologies: an O(devices) regression is visible
                       even below the hard gather-freedom assert.

The registry measurement side (`measure_entry`) compiles on simulated
CPU devices (`meshlib.cpu_devices`); the judge (`judge_record` /
`judge_scaling`) is pure dicts-in/dicts-out so tests can fabricate
records the way tests/test_bench_guard.py fabricates bench rows.
Manifest file I/O and the tree-wide jit-site scan live in
tools/hlo_lint.py — this module never touches the filesystem.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import serf, swim
from consul_tpu.parallel import mesh as meshlib
from consul_tpu.utils import donation
from consul_tpu.utils.sync import backend_honors_donation

# ---------------------------------------------------------------- rules
# (promoted single implementations — every former ad-hoc audit block is
# a shim over these)

COLLECTIVE_FAMILIES = ("collective-permute", "all-gather", "all-reduce",
                       "all-to-all")


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Instruction census of the cross-shard traffic GSPMD inserted:
    collective-permutes ARE the ring rumor/probe exchange
    (ops/rolls.py decomposition); all-gathers should only ever touch
    replicated [U]-sized tables (full_gather_ops proves it).  Promoted
    from tools/profile_swim.py count_collectives."""
    out = {}
    for op in COLLECTIVE_FAMILIES:
        c = hlo_text.count(f" {op}(") + hlo_text.count(f" {op}-start(")
        if c:
            out[op] = c
    return out


def alias_entries(hlo_text: str) -> int:
    """Number of input→output alias pairs the compiled module header
    declares, e.g. ``input_output_alias={ {0}: (1, {0}, may-alias) }``.
    This is the donation EVIDENCE: `donate_argnums` that XLA could not
    honor simply produces zero entries (plus a once-per-process
    warning nobody reads) and silently double-buffers the carry."""
    marker = "input_output_alias={"
    start = hlo_text.find(marker)
    if start < 0:
        return 0
    # the alias map nests braces ({output index}: (param, {param
    # index}, kind)), so walk to the matching close instead of a regex
    i = start + len(marker) - 1
    depth = 0
    for j in range(i, min(len(hlo_text), i + 1_000_000)):
        ch = hlo_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                block = hlo_text[i:j + 1]
                return block.count("(")
    return 0


def audit_compiled(compiled_or_text, n_nodes: int, name: str) -> dict:
    """THE gather-freedom + census audit every former ad-hoc block now
    calls: asserts zero all-gathers materializing a node-axis buffer
    (meshlib.full_gather_ops) and returns the collective census.
    Raises AssertionError naming `name` on violation."""
    hlo = compiled_or_text if isinstance(compiled_or_text, str) \
        else compiled_or_text.as_text()
    bad = meshlib.full_gather_ops(hlo, n_nodes)
    assert not bad, (
        f"{name}: {len(bad)} all-gather(s) of full node-axis buffers "
        f"— first: {bad[0][:200]}")
    return {"collectives": collective_census(hlo),
            "full_node_gathers": 0}


def compiled_stats(compiled) -> dict:
    """XLA's own cost/memory analysis of one compiled executable:
    flops, HBM bytes touched, argument/output/temp sizes and the
    peak-buffer proxy (output+temp) the budget rule judges.  Promoted
    from tools/profile_swim.py compile_with_stats."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        for k_out, k_in in (("flops", "flops"),
                            ("bytes_accessed", "bytes accessed")):
            v = ca.get(k_in)
            if v is not None:
                out[k_out] = float(v)
    except Exception:       # pragma: no cover - backend-specific
        pass
    try:
        ma = compiled.memory_analysis()
        for k_out, k_in in (("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("temp_bytes", "temp_size_in_bytes")):
            v = getattr(ma, k_in, None)
            if v is not None:
                out[k_out] = int(v)
    except Exception:       # pragma: no cover - backend-specific
        pass
    if "output_bytes" in out and "temp_bytes" in out:
        out["peak_bytes"] = out["output_bytes"] + out["temp_bytes"]
    return out


def cache_size(jfn) -> Optional[int]:
    """Dispatch-cache entry count of a jitted callable (None when this
    jax build hides it) — the compile-count ledger's raw number."""
    return int(jfn._cache_size()) if hasattr(jfn, "_cache_size") else None


def assert_single_compile(jfn_or_count, name: str) -> Optional[int]:
    """The recompile-hygiene audit bench/scale_sweep shim over: the
    dispatch cache must hold exactly ONE entry (a second means the
    static config was perturbed mid-run and a timed window silently
    included an XLA compile).  Accepts a jitted callable or an
    already-read count; returns the count."""
    c = jfn_or_count if (jfn_or_count is None
                         or isinstance(jfn_or_count, int)) \
        else cache_size(jfn_or_count)
    assert c in (None, 1), f"{name}: compiled {c}x (expected exactly 1)"
    return c


def bytes_per_slot(state, slots: int) -> int:
    """Dtype-width ledger: total bytes of every node-axis leaf in the
    state pytree, per node slot.  A widened store (int8 → int32 on a
    [N, U] buffer) moves this number and nothing else — the aval-level
    complement of the dtype-discipline source lint."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        shape = getattr(leaf, "shape", ())
        if slots in shape:
            total += int(leaf.nbytes) // slots
    return total


# ------------------------------------------------------------- registry

@dataclasses.dataclass
class Program:
    """One buildable jit entry point at one topology: the jitted
    callable, its example args, and the expectations the rules check.
    `rebind` maps (args, first-call output) to the second call's args —
    required when `donate` consumes the carry."""
    jfn: Any
    args: tuple
    n_nodes: int                 # node-axis extent for gather-freedom
    state: Any                   # pytree the dtype ledger sums over
    slots: int                   # node-slot divisor for the ledger
    mesh_shape: Optional[dict] = None
    donate: bool = False
    rebind: Optional[Callable[[tuple, Any], tuple]] = None


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    """A registered production jit entry point: how to build its
    Program per topology, which device counts it must hold its
    contracts on, and which `jax.jit` call sites in the tree it
    covers for the registry-parity check (tools/hlo_lint.py)."""
    name: str
    build: Callable[[int, list], Program]
    topologies: Tuple[int, ...]
    covers: Tuple[Tuple[str, str], ...]


_SELF = "consul_tpu/parallel/hlo_audit.py"
_N = 256          # bounded pool: shardable to 8 devices (256 >= 4*8)
_TICKS = 8
_VICTIM = 3


def _serf_setup(n_devices: int, devs: list):
    """Shared serf fixture: params + state, sharded when n_devices > 1
    (mirroring bench.run_convergence: single-device production runs
    carry no mesh at all)."""
    params = serf.make_params(
        GossipConfig.lan(),
        SimConfig(n_nodes=_N, rumor_slots=16, alloc_cap=8, p_loss=0.01,
                  seed=7, shard_blocks=n_devices if n_devices > 1 else 1))
    s = serf.init_state(params)
    sharding = mesh_shape = mesh = None
    if n_devices > 1:
        mesh = meshlib.make_mesh(devs[:n_devices])
        sharding = meshlib.state_sharding(s, mesh)
        s = jax.device_put(s, sharding)
        mesh_shape = dict(mesh.shape)
    return params, s, sharding, mesh, mesh_shape


def _shard_like_state(x, mesh):
    """Place a bare node-axis array the way state_sharding would."""
    if mesh is None:
        return x
    return jax.device_put(x, meshlib.state_sharding(x, mesh))


def _build_scan(d: int, devs: list) -> Program:
    """The bench's timed inner loop (bench.py run_convergence): the
    donated fixed-length serf scan, out-shardings threaded."""
    params, s, sharding, _, mesh_shape = _serf_setup(d, devs)
    out_sh = (sharding, None) if sharding is not None else None
    run = jax.jit(serf.run, static_argnums=(0, 2, 3),
                  donate_argnums=donation(1), out_shardings=out_sh)
    return Program(jfn=run, args=(params, s, _TICKS, _VICTIM),
                   n_nodes=_N, state=s, slots=_N, mesh_shape=mesh_shape,
                   donate=bool(donation(1)),
                   rebind=lambda a, out: (a[0], out[0], a[2], a[3]))


def _build_step(d: int, devs: list) -> Program:
    """The oracle's tick (oracle.py _step): undonated — readers hold
    references to the carry across advance() calls."""
    params, s, sharding, _, mesh_shape = _serf_setup(d, devs)
    step = jax.jit(serf.step, static_argnums=0, out_shardings=sharding)
    return Program(jfn=step, args=(params, s), n_nodes=_N, state=s,
                   slots=_N, mesh_shape=mesh_shape)


def _read_kernel(fn, static, extra_args):
    """Builder factory for the oracle's gather-free read kernels:
    device-side reductions whose outputs are O(page), never O(N)."""
    def build(d: int, devs: list) -> Program:
        params, s, _, mesh, mesh_shape = _serf_setup(d, devs)
        jfn = jax.jit(fn, static_argnums=static)
        return Program(jfn=jfn, args=(params, s) + extra_args(mesh),
                       n_nodes=_N, state=s, slots=_N,
                       mesh_shape=mesh_shape)
    return build


def _build_coord_row(d: int, devs: list) -> Program:
    """oracle.py's coordinate-row kernel: one masked O(D) row read
    (oracle._coord_row — the gather-free rewrite this framework's
    first tree-wide run forced)."""
    from consul_tpu import oracle as _oracle
    _, s, _, _, mesh_shape = _serf_setup(d, devs)
    jfn = jax.jit(_oracle._coord_row)
    return Program(jfn=jfn, args=(s.coords, jnp.int32(5)), n_nodes=_N,
                   state=s.coords, slots=_N, mesh_shape=mesh_shape)


def _build_chaos_swim(d: int, devs: list) -> Program:
    """chaos.py compiled_swim_run's shape: a monitored swim.run chunk
    closed over params/ticks/monitor (single-device — the nemesis
    evolves the fault schedule on the host between scans)."""
    params = swim.make_params(
        GossipConfig.lan(),
        SimConfig(n_nodes=_N, rumor_slots=16, p_loss=0.02, seed=7))
    st = swim.init_state(params)
    jfn = jax.jit(lambda s: swim.run(params, s, _TICKS, _VICTIM))
    return Program(jfn=jfn, args=(st,), n_nodes=_N, state=st, slots=_N)


def _build_wan(d: int, devs: list) -> Program:
    """The 2-D dc x nodes federation program (meshlib.make_wan_mesh):
    per-DC LAN pools sharded on `nodes`, dc batch on `dc`, WAN pool on
    `nodes` — the multi-slice/DCN layout, at the exact shape
    test_sharding proves against single-device (64 nodes/dc, 2 dcs x
    4 node shards).  The entry pins topologies=(8,): GSPMD's
    gather-free lowering of the cross-DC bulk step is specific to
    this shape — 2x2 meshes and 32-node pools today emit bounded
    [dc, N] all-gathers there (measured, not fixed here; the budget
    would catch a regression OF THE PROVEN SHAPE, which is what ships
    to the chip)."""
    from consul_tpu.models import wan
    n_per_dc = 64
    params = wan.make_params(n_dcs=2, nodes_per_dc=n_per_dc,
                             servers_per_dc=4, p_loss=0.02, seed=7,
                             rumor_slots=8, event_slots=8,
                             shard_blocks=max(d // 2, 1))
    s0 = wan.init_state(params)
    mesh = meshlib.make_wan_mesh(devs[:d], n_dcs=2)
    sharding = meshlib.wan_state_sharding(s0, mesh)
    sh = jax.device_put(s0, sharding)
    run = jax.jit(wan.run, static_argnums=(0, 2), out_shardings=sharding)
    return Program(jfn=run, args=(params, sh, 5), n_nodes=n_per_dc,
                   state=sh, slots=n_per_dc,
                   mesh_shape=dict(mesh.shape))


def _counts_args(mesh):
    return (_shard_like_state(jnp.ones((_N,), bool), mesh),)


def _page_args(mesh):
    return (jnp.arange(8, dtype=jnp.int32),)


def _delta_args(mesh):
    prev = _shard_like_state(jnp.full((_N,), -1, jnp.int8), mesh)
    prov = _shard_like_state(jnp.ones((_N,), bool), mesh)
    return (prev, prov, 16)


def _rtt_args(mesh):
    return (jnp.int32(0), jnp.arange(8, dtype=jnp.int32),
            jnp.ones((8,), bool))


def _shard_metrics_args(mesh):
    return (8,)


REGISTRY: Tuple[EntrySpec, ...] = (
    EntrySpec("serf.scan", _build_scan, (1, 2, 4, 8),
              covers=(("bench.py", "serf.run"), (_SELF, "serf.run"))),
    EntrySpec("serf.step", _build_step, (1, 2, 4, 8),
              covers=(("consul_tpu/oracle.py", "serf.step"),
                      (_SELF, "serf.step"))),
    EntrySpec("serf.metrics",
              _read_kernel(serf.metrics_vector, 0, lambda m: ()),
              (1, 8),
              covers=(("bench.py", "serf.metrics_vector"),
                      ("consul_tpu/oracle.py", "serf.metrics_vector"))),
    EntrySpec("serf.status_vector",
              _read_kernel(serf.status_vector, 0, lambda m: ()),
              (1, 8),
              covers=()),
    EntrySpec("serf.shard_metrics",
              _read_kernel(serf.shard_metrics, (0, 2),
                           _shard_metrics_args),
              (1, 8),
              covers=(("consul_tpu/oracle.py", "serf.shard_metrics"),)),
    EntrySpec("oracle.membership_counts",
              _read_kernel(serf.membership_counts, 0, _counts_args),
              (1, 8),
              covers=(("consul_tpu/oracle.py", "serf.membership_counts"),)),
    EntrySpec("oracle.membership_page",
              _read_kernel(serf.membership_page, 0, _page_args),
              (1, 8),
              covers=(("consul_tpu/oracle.py", "serf.membership_page"),)),
    EntrySpec("oracle.membership_delta",
              _read_kernel(serf.membership_delta, (0, 4), _delta_args),
              (1, 8),
              covers=(("consul_tpu/oracle.py", "serf.membership_delta"),)),
    EntrySpec("oracle.rtt_order",
              _read_kernel(serf.rtt_order, 0, _rtt_args),
              (1, 8),
              covers=(("consul_tpu/oracle.py", "serf.rtt_order"),)),
    EntrySpec("oracle.coord_row", _build_coord_row, (1, 8),
              covers=(("consul_tpu/oracle.py", "_coord_row"),
                      (_SELF, "_oracle._coord_row"))),
    EntrySpec("chaos.swim_run", _build_chaos_swim, (1,),
              covers=(("consul_tpu/chaos.py", "<lambda>"),
                      (_SELF, "<lambda>"))),
    # one topology: the 2 dcs x 4 node shards shape PR 6 proved
    # gather-free (test_sharding's audited program); smaller wan
    # meshes lower with bounded [dc, N] gathers in the cross-DC bulk
    # step today — see _build_wan's docstring
    EntrySpec("wan.mesh2d", _build_wan, (8,),
              covers=((_SELF, "wan.run"),)),
)

# jax.jit call sites under consul_tpu/ + bench.py that are deliberately
# NOT registry entries — each with its reason (the PR 5 suppression
# discipline; a stale entry fails the parity check)
SUPPRESSED_JIT_SITES: Dict[Tuple[str, str], str] = {
    ("consul_tpu/utils/sync.py", "<lambda>"):
        "donation-capability probe: one trivial add compiled once per "
        "backend to read input_output_alias support — not a "
        "production kernel, no state, no topology axis",
    (_SELF, "fn"):
        "the _read_kernel builder factory: `fn` is whichever oracle "
        "read kernel the registry entry passed in — every concrete "
        "kernel it wraps IS a registry entry (serf.metrics/"
        "status_vector/shard_metrics, oracle.membership_*/rtt_order)",
}


def registry_parity(sites: List[Tuple[str, str]]) -> dict:
    """Every scanned `jax.jit` call site must be covered by a registry
    entry or suppressed with a reason; covers/suppressions pointing at
    sites that no longer exist are STALE and fail too (the PR 5
    empty-baseline discipline).  `sites` comes from the AST scan in
    tools/hlo_lint.py — this stays pure so tests can fabricate it."""
    scanned = set(sites)
    covered = {c for spec in REGISTRY for c in spec.covers}
    suppressed = set(SUPPRESSED_JIT_SITES)
    uncovered = sorted(scanned - covered - suppressed)
    stale = sorted((covered | suppressed) - scanned)
    return {"ok": not uncovered and not stale,
            "sites": len(scanned),
            "uncovered": [list(s) for s in uncovered],
            "stale": [list(s) for s in stale]}


# ---------------------------------------------------------- measurement

def topology_stamp(n_devices: int, mesh_shape: Optional[dict]) -> dict:
    """The BENCH_BASELINE-style stamp every record carries, so the
    judge can refuse cross-topology comparisons instead of silently
    judging CPU numbers against chip budgets."""
    return {"backend": jax.default_backend(), "devices": n_devices,
            "mesh_shape": mesh_shape}


def measure_entry(spec: EntrySpec, n_devices: int, devs: list) -> dict:
    """Build + AOT-compile one entry at one topology and extract every
    number the rules judge.  Also dispatches the jitted callable twice
    (rebinding the donated carry) so the compile-count ledger reads
    the real dispatch cache, not the AOT path."""
    prog = spec.build(n_devices, list(devs))
    compiled = prog.jfn.lower(*prog.args).compile()
    hlo = compiled.as_text()
    record = {
        "topology": topology_stamp(n_devices, prog.mesh_shape),
        **audit_compiled(hlo, prog.n_nodes,
                         f"{spec.name}@{n_devices}d"),
        "alias_entries": alias_entries(hlo),
        "donate_expected": prog.donate,
        "donation_capable": backend_honors_donation(),
        "bytes_per_slot": bytes_per_slot(prog.state, prog.slots),
        **compiled_stats(compiled),
    }
    # compile-count = dispatch-cache GROWTH across the two calls, not
    # the absolute size: pjit shares its cache across jax.jit wrappers
    # of the same function object, so another topology's measurement
    # earlier in the process is visible in _cache_size() (and the AOT
    # compile above contributes nothing to it)
    pre = cache_size(prog.jfn)
    out = prog.jfn(*prog.args)
    jax.block_until_ready(out)
    args2 = prog.rebind(prog.args, out) if prog.rebind is not None \
        else prog.args
    out2 = prog.jfn(*args2)
    jax.block_until_ready(out2)
    post = cache_size(prog.jfn)
    record["compiles"] = None if post is None else post - (pre or 0)
    return record


# ---------------------------------------------------------------- judge

def judge_record(run: dict, base: dict, tolerance: float) -> dict:
    """Judge one measured record against its committed budget.  A
    topology-stamp mismatch REFUSES (verdict "topology") rather than
    judging — chip budgets must never gate CPU lowerings or vice
    versa; re-baseline on the new topology instead
    (hlo_lint --update-baseline)."""
    rt = run.get("topology") or {}
    bt = base.get("topology") or {}
    if bt and rt and any(rt.get(k) != bt.get(k)
                         for k in ("backend", "devices", "mesh_shape")):
        return {"ok": False, "verdict": "topology", "failures": [],
                "baseline_topology": bt, "run_topology": rt}
    fails: List[dict] = []

    def fail(rule, detail):
        fails.append({"rule": rule, "detail": detail})

    if run.get("full_node_gathers"):
        fail("gather-freedom",
             f"{run['full_node_gathers']} all-gather(s) materialize a "
             f"node-axis buffer")
    base_census = base.get("collectives") or {}
    for fam, n in sorted((run.get("collectives") or {}).items()):
        budget = base_census.get(fam)
        if budget is None:
            fail("collective-family",
                 f"unexpected {fam} x{n} (family absent from budget)")
        elif n > budget:
            fail("collective-census", f"{fam} count {n} > budget {budget}")
    if run.get("donate_expected") and run.get("donation_capable") \
            and not run.get("alias_entries"):
        fail("donation",
             "donation requested and backend honors aliasing, but the "
             "compiled executable aliases nothing — the silent-copy "
             "failure mode (every donated carry double-buffers)")
    bps, base_bps = run.get("bytes_per_slot"), base.get("bytes_per_slot")
    if bps and base_bps and bps > base_bps:
        fail("dtype-width",
             f"state widened to {bps} B/slot (budget {base_bps} — the "
             f"PR 2 narrowing)")
    for k in ("flops", "peak_bytes"):
        rv, bv = run.get(k), base.get(k)
        if rv and bv and abs(rv - bv) > tolerance * bv:
            fail("budget",
                 f"{k} {rv} outside ±{tolerance:.0%} of budget {bv}")
    if run.get("compiles") not in (None, 1):
        fail("compile-count",
             f"{run['compiles']} dispatch-cache entries (expected "
             f"exactly 1 compile per topology)")
    return {"ok": not fails,
            "verdict": "ok" if not fails else "violation",
            "failures": fails}


def judge_scaling(records_by_devices: Dict[int, dict],
                  tolerance: float) -> dict:
    """The permute-law judge across topologies of ONE entry: ring
    rotations lower to log2(devices) collective-permutes each
    (ops/rolls.py), so permutes/log2(d) must not GROW with device
    count — growth means a rotation regressed toward O(devices)
    traffic.  The check is one-sided: the ratio at the smallest
    sharded topology is the reference, and larger topologies may only
    exceed it by the tolerance.  A ratio that shrinks with devices is
    sub-log2 scaling — an improvement, never a violation."""
    ratios = {}
    for d, rec in records_by_devices.items():
        if d > 1:
            permutes = (rec.get("collectives") or {}).get(
                "collective-permute", 0)
            ratios[d] = permutes / math.log2(d)
    if len(ratios) < 2:
        return {"ok": True, "rule": "permute-scaling", "ratios": ratios,
                "note": "needs >=2 sharded topologies"}
    ref = ratios[min(ratios)]
    hi = max(ratios.values())
    ok = hi <= max(ref, 1e-9) * (1.0 + tolerance)
    return {"ok": ok, "rule": "permute-scaling",
            "ratios": {str(d): round(r, 2) for d, r in ratios.items()},
            "growth_ratio": round(hi / max(ref, 1e-9), 3)}
