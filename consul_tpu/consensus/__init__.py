"""Host-side consensus: Raft leader election + replicated log + FSM.

The reference keeps strong consistency in hashicorp/raft (go.mod:55,
wired in agent/consul/server.go:674 setupRaft); SURVEY.md §2.1 marks this
layer host-side for the TPU build — the cluster-scale work (membership,
coordinates, dissemination) lives on the device, while the 3-7 server
control plane stays a small, deterministic host protocol.
"""

from consul_tpu.consensus.raft import (  # noqa: F401
    InMemTransport, NotLeaderError, RaftConfig, RaftNode,
)
