"""Durable raft state: write-ahead log + vote/term + snapshot on disk.

The raft-boltdb role (reference agent/consul/server.go:728
`raftboltdb.NewBoltStore(.../raft.db)` plus the FileSnapshotStore two
lines up): every appended entry, every term/vote change, and every
snapshot reaches disk with fsync BEFORE the node acknowledges it to the
cluster, so a whole-fleet power loss recovers to the last committed
write instead of the last operator snapshot (VERDICT r2 missing #2).

Layout under one directory:

  LOCK        flock'd for the process lifetime — two processes on one
              data dir fail fast instead of interleaving WAL frames
              (raft-boltdb locks raft.db the same way)
  meta.json   {"term": T, "voted_for": ...}       atomic tmp+rename
  snap.json   {"index": N, "term": T, "data": .}  atomic tmp+rename
  wal.log     framed JSON records, append-only:
                {"t":"e","i":idx,"tm":term,"c":cmd,"n":noop}  entry
                {"t":"trunc","i":idx}     delete entries >= idx
                {"t":"base","i":N,"tm":T} log window base moved

The log window base can trail the snapshot index by snapshot_trailing
entries (raft keeps a catch-up window behind each snapshot), so `base`
records and snap.json carry independent horizons.  The WAL is replayed
on load; entries <= base are dropped (their effect lives in snap.json).
Compaction appends a cheap base record each time and only REWRITES the
WAL once it holds ~rewrite_threshold dead records, bounding both disk
growth and the time spent inside a single compaction.  Torn tails (a
crash mid-append) are detected by the length prefix and truncated away
— everything before the tear was already fsynced and survives.
"""

from __future__ import annotations

import fcntl
import json
import os
import struct
import tempfile
from typing import Any, Dict, Optional, Tuple


def _atomic_write(path: str, obj: Any) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(json.dumps(obj).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class DataDirLockedError(Exception):
    """Another live process holds this raft data directory."""


class DurableLog:
    """One raft node's persistent state under `directory`."""

    def __init__(self, directory: str, rewrite_threshold: int = 8192):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        # exclusive dir lock FIRST: a second process must fail loudly
        # before it can interleave a single WAL byte
        self._lockfd = os.open(os.path.join(directory, "LOCK"),
                               os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(self._lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(self._lockfd)
            raise DataDirLockedError(
                f"raft data dir {directory!r} is locked by a live "
                f"process")
        self._wal_path = os.path.join(directory, "wal.log")
        self._meta_path = os.path.join(directory, "meta.json")
        self._snap_path = os.path.join(directory, "snap.json")
        self._wal = open(self._wal_path, "ab")
        self._dirty = False
        self.rewrite_threshold = rewrite_threshold
        self._records_since_rewrite = 0

    # ------------------------------------------------------------ recovery

    def load(self) -> Optional[dict]:
        """Replay persisted state; None when this directory is fresh.

        Returns {"term", "voted_for", "base", "base_term",
        "snap_index", "snap_term", "snapshot" (or None),
        "entries": {idx: (term, cmd, noop)}}."""
        have_meta = os.path.exists(self._meta_path)
        meta = {"term": 0, "voted_for": None}
        if have_meta:
            with open(self._meta_path, "rb") as f:
                meta = json.loads(f.read())
        snap = None
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                snap = json.loads(f.read())
        snap_index = snap["index"] if snap else 0
        snap_term = snap["term"] if snap else 0
        base, base_term = 0, 0
        entries: Dict[int, Tuple[int, Any, bool]] = {}
        wal_records = 0
        for rec in self._replay_wal():
            wal_records += 1
            t = rec["t"]
            if t == "e":
                entries[rec["i"]] = (rec["tm"], rec["c"],
                                     rec.get("n", False))
            elif t == "trunc":
                for i in [i for i in entries if i >= rec["i"]]:
                    del entries[i]
            elif t == "base":
                if rec["i"] >= base:
                    base, base_term = rec["i"], rec["tm"]
        if snap is not None and base == 0:
            # snapshot without any base record (install path)
            base, base_term = snap_index, snap_term
        for i in [i for i in entries if i <= base]:
            del entries[i]
        self._records_since_rewrite = wal_records
        if not have_meta and not entries and snap is None \
                and wal_records == 0:
            return None
        return {"term": meta["term"], "voted_for": meta["voted_for"],
                "base": base, "base_term": base_term,
                "snap_index": snap_index, "snap_term": snap_term,
                "snapshot": snap["data"] if snap else None,
                "entries": entries}

    def _replay_wal(self):
        """Yield WAL records, truncating a torn tail in place."""
        try:
            f = open(self._wal_path, "rb")
        except FileNotFoundError:
            return
        good = 0
        with f:
            while True:
                head = f.read(4)
                if len(head) < 4:
                    break
                (ln,) = struct.unpack(">I", head)
                blob = f.read(ln)
                if len(blob) < ln:
                    break                      # torn mid-record
                try:
                    rec = json.loads(blob)
                except ValueError:
                    break                      # torn inside the json
                good = f.tell()
                yield rec
        size = os.path.getsize(self._wal_path)
        if good != size:
            # crash mid-append: drop the tear (it was never acked)
            self._wal.close()
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            self._wal = open(self._wal_path, "ab")

    # ------------------------------------------------------------- writes

    def _frame(self, rec: dict) -> None:
        blob = json.dumps(rec).encode()
        self._wal.write(struct.pack(">I", len(blob)) + blob)
        self._dirty = True
        self._records_since_rewrite += 1

    def append(self, idx: int, term: int, cmd: Any,
               noop: bool = False) -> None:
        self._frame({"t": "e", "i": idx, "tm": term, "c": cmd,
                     "n": noop})

    def truncate_from(self, idx: int) -> None:
        """Conflict resolution deleted entries >= idx."""
        self._frame({"t": "trunc", "i": idx})

    def sync(self) -> None:
        """fsync pending WAL records; MUST run before the node
        acknowledges those entries to anyone (append_reply, own
        match-index count)."""
        if not self._dirty:
            return
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._dirty = False

    def set_term_vote(self, term: int, voted_for: Optional[str]) -> None:
        """Durable BEFORE any message carrying the new term/vote leaves
        this node (Raft's persistent-state rule)."""
        _atomic_write(self._meta_path, {"term": term,
                                        "voted_for": voted_for})

    def save_snapshot(self, snap_index: int, snap_term: int, data: Any,
                      live_entries: Dict[int, Tuple[int, Any, bool]],
                      base: Optional[int] = None,
                      base_term: Optional[int] = None) -> None:
        """Persist a snapshot and move the log window base (defaults to
        the snapshot index — the InstallSnapshot shape; compaction
        passes a trailing base so the catch-up window survives
        restarts).

        Cheap path: snap.json + one appended base record (two fsyncs).
        The WAL is only REWRITTEN to the live window once it carries
        ~rewrite_threshold records, so a single compaction never stalls
        the tick thread on an unbounded rewrite."""
        if base is None:
            base, base_term = snap_index, snap_term
        _atomic_write(self._snap_path,
                      {"index": snap_index, "term": snap_term,
                       "data": data})
        self._frame({"t": "base", "i": base, "tm": base_term})
        self.sync()
        if self._records_since_rewrite < self.rewrite_threshold:
            return
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".wal-")
        n = 1
        with os.fdopen(fd, "wb") as f:
            rec = json.dumps({"t": "base", "i": base,
                              "tm": base_term}).encode()
            f.write(struct.pack(">I", len(rec)) + rec)
            for i in sorted(live_entries):
                if i <= base:
                    continue
                tm, cmd, noop = live_entries[i]
                blob = json.dumps({"t": "e", "i": i, "tm": tm,
                                   "c": cmd, "n": noop}).encode()
                f.write(struct.pack(">I", len(blob)) + blob)
                n += 1
            f.flush()
            os.fsync(f.fileno())
        self._wal.close()
        os.replace(tmp, self._wal_path)
        dirfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._wal = open(self._wal_path, "ab")
        self._dirty = False
        self._records_since_rewrite = n

    def close(self) -> None:
        self.sync()
        self._wal.close()
        try:
            fcntl.flock(self._lockfd, fcntl.LOCK_UN)
        finally:
            os.close(self._lockfd)
