"""Durable raft state: checksummed write-ahead log + vote/term +
snapshot on disk.

The raft-boltdb role (reference agent/consul/server.go:728
`raftboltdb.NewBoltStore(.../raft.db)` plus the FileSnapshotStore two
lines up): every appended entry, every term/vote change, and every
snapshot reaches disk with fsync BEFORE the node acknowledges it to the
cluster, so a whole-fleet power loss recovers to the last committed
write instead of the last operator snapshot (VERDICT r2 missing #2).

Layout under one directory:

  LOCK        flock'd for the process lifetime — two processes on one
              data dir fail fast instead of interleaving WAL frames
              (raft-boltdb locks raft.db the same way)
  meta.json   {"term": T, "voted_for": ...}       checked, atomic
  snap.json   {"index": N, "term": T, "data": .}  checked, atomic
  *.prev      the previous generation of a checked file — the fallback
              when a crash or a reordering disk corrupts the current
  wal.log     framed JSON records, append-only:
                {"t":"e","i":idx,"tm":term,"c":cmd,"n":noop}  entry
                {"t":"trunc","i":idx}     delete entries >= idx
                {"t":"base","i":N,"tm":T} log window base moved

WAL frame format v2: `b"W2" | len:u32 | crc32:u32 | payload` — the CRC
covers the payload, so single-bit rot is detected instead of replaying
as committed state.  v1 frames (`len:u32 | payload`, written before
this format existed) are still read: the magic can't collide with a v1
length prefix because record payloads are far below 2^24 bytes, so the
first byte of a v1 frame is always 0x00.  Replay stops at the first
bad frame and truncates there — a TORN tail (short frame) was never
acked and is dropped silently; a CORRUPT frame (checksum mismatch) is
quarantined at exactly that frame, never earlier, so every record
acked before the rot survives, and the loss is surfaced through the
`consul.raft.recovery.*` counters and the load() recovery report
rather than silently replayed.

meta.json / snap.json are wrapped as {"v":2,"crc":...,"data":...} and
rotated through a `.prev` generation on every write: if the current
file fails its checksum (bit rot, or a rename that outran its data on
a reordering disk) the previous generation is used and the fallback is
counted.  Plain pre-v2 JSON files load unchecked (backward compat).

The log window base can trail the snapshot index by snapshot_trailing
entries (raft keeps a catch-up window behind each snapshot), so `base`
records and snap.json carry independent horizons.  Compaction appends
a cheap base record each time and only REWRITES the WAL once it holds
~rewrite_threshold dead records; a failed rewrite (ENOSPC) keeps the
old WAL intact and retries at the next compaction.

Every file operation goes through the `consul_tpu.storage` seam so the
storage nemesis (chaos.FaultyStorage) can inject torn writes, lost and
failing fsyncs, ENOSPC, and rename reordering deterministically —
tools/crash_matrix.py proves recovery at every one of these I/O
boundaries.
"""

from __future__ import annotations

import fcntl
import json
import os
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

from consul_tpu import storage, telemetry

WAL_MAGIC = b"W2"


def _dump_checked(obj: Any) -> bytes:
    """Serialize with an embedded CRC32 over the canonical payload."""
    payload = json.dumps(obj, sort_keys=True).encode()
    return json.dumps({"v": 2, "crc": zlib.crc32(payload) & 0xFFFFFFFF,
                       "data": obj}, sort_keys=True).encode()


def _parse_checked(blob: bytes) -> Tuple[Any, str]:
    """(data, status) where status is 'ok' (v2, checksum good), 'v1'
    (pre-checksum plain JSON, accepted unchecked), or 'corrupt'."""
    try:
        rec = json.loads(blob)
    except ValueError:
        return None, "corrupt"
    if isinstance(rec, dict) and rec.get("v") == 2 and "crc" in rec \
            and "data" in rec:
        payload = json.dumps(rec["data"], sort_keys=True).encode()
        if zlib.crc32(payload) & 0xFFFFFFFF == rec["crc"]:
            return rec["data"], "ok"
        return None, "corrupt"
    if isinstance(rec, dict) and "crc" in rec and "data" in rec:
        # a v1 file never carried these keys: this is a v2 envelope
        # whose version/crc fields themselves rotted — not legacy data
        return None, "corrupt"
    return rec, "v1"


class DataDirLockedError(Exception):
    """Another live process holds this raft data directory."""


class StorageCorruptionError(Exception):
    """A just-written durable file failed its read-back verification."""


class PersistentStateCorruptError(Exception):
    """meta.json (term/vote) failed its checksum on BOTH generations,
    or rotted after being acked.  Unlike snapshots and log entries —
    which replication repairs — a rewound vote can elect two leaders
    in one term (Raft's persistent-state rule), so the only safe
    answers are fail-stop or operator-driven fresh rejoin (wipe the
    data dir)."""


class DurableLog:
    """One raft node's persistent state under `directory`."""

    def __init__(self, directory: str, rewrite_threshold: int = 8192,
                 io: Optional[storage.StorageOps] = None):
        self.dir = directory
        self.io = io or storage.OS
        os.makedirs(directory, exist_ok=True)
        # exclusive dir lock FIRST: a second process must fail loudly
        # before it can interleave a single WAL byte
        self._lockfd = os.open(os.path.join(directory, "LOCK"),
                               os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(self._lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(self._lockfd)
            raise DataDirLockedError(
                f"raft data dir {directory!r} is locked by a live "
                f"process")
        self._wal_path = os.path.join(directory, "wal.log")
        self._meta_path = os.path.join(directory, "meta.json")
        self._snap_path = os.path.join(directory, "snap.json")
        self._wal = self.io.open_append(self._wal_path)
        self._dirty = False
        self.rewrite_threshold = rewrite_threshold
        self._records_since_rewrite = 0
        # filled by load(): what recovery had to repair/fall back on
        self.recovery: Dict[str, Any] = {}

    # ------------------------------------------------------------ recovery

    def _load_checked(self, path: str,
                      validate=None) -> Tuple[Any, bool, bool]:
        """(data, corrupt_primary, used_prev): read a checked file,
        falling back to its previous generation when the current one
        is missing mid-rotation, fails its checksum, or fails the
        caller's shape validator (rot inside an unchecked v1 file)."""
        corrupt = False
        for p, is_prev in ((path, False), (path + ".prev", True)):
            try:
                with self.io.open_read(p) as f:
                    blob = f.read()
            except FileNotFoundError:
                continue
            data, status = _parse_checked(blob)
            if status != "corrupt" and (validate is None
                                        or validate(data)):
                return data, corrupt, is_prev
            corrupt = True
        return None, corrupt, False

    def load(self) -> Optional[dict]:
        """Replay persisted state; None when this directory is fresh.

        Returns {"term", "voted_for", "base", "base_term",
        "snap_index", "snap_term", "snapshot" (or None),
        "entries": {idx: (term, cmd, noop)}, "recovery": {...}}.
        The "recovery" dict reports what load() had to repair —
        torn_tail / corrupt_frame counts, meta/snap generation
        fallbacks — and the same facts land on the
        consul.raft.recovery.* counters."""
        rec: Dict[str, Any] = {
            "torn_tail": 0, "corrupt_frame": 0, "v1_frames": 0,
            "dropped_bytes": 0, "meta_fallback": False,
            "meta_lost": False, "snap_fallback": False,
            "snap_lost": False,
        }
        meta, m_corrupt, m_prev = self._load_checked(
            self._meta_path,
            validate=lambda d: isinstance(d, dict) and "term" in d)
        have_meta = meta is not None
        rec["meta_fallback"] = m_prev and not m_corrupt
        rec["meta_lost"] = m_corrupt
        if m_corrupt:
            # A MISSING current generation is a crash mid-rotation: the
            # in-flight state was never acked (set_term_vote persists
            # BEFORE any message leaves), so .prev is the truth and the
            # fallback above is safe.  A current generation that fails
            # its CHECKSUM is different: it was fully written and acked
            # before it rotted, so rewinding to .prev could re-vote in
            # a term this node already voted in — two leaders, one
            # term.  Fail stop; the operator wipes the dir and the
            # node rejoins fresh (raft-boltdb/etcd take the same
            # stance on corrupt vote state).
            telemetry.incr_counter(("raft", "recovery", "meta_lost"))
            raise PersistentStateCorruptError(
                f"{self._meta_path} failed checksum verification; "
                f"term/vote cannot be trusted — wipe the data dir to "
                f"rejoin as a fresh node")
        if meta is None:
            meta = {"term": 0, "voted_for": None}
        snap, s_corrupt, s_prev = self._load_checked(
            self._snap_path,
            validate=lambda d: isinstance(d, dict) and "index" in d
            and "term" in d and "data" in d)
        rec["snap_fallback"] = s_prev
        rec["snap_lost"] = s_corrupt and snap is None
        snap_index = snap["index"] if snap else 0
        snap_term = snap["term"] if snap else 0
        base, base_term = 0, 0
        entries: Dict[int, Tuple[int, Any, bool]] = {}
        wal_records = 0
        for r in self._replay_wal(rec):
            wal_records += 1
            t = r["t"]
            if t == "e":
                entries[r["i"]] = (r["tm"], r["c"], r.get("n", False))
            elif t == "trunc":
                for i in [i for i in entries if i >= r["i"]]:
                    del entries[i]
            elif t == "base":
                if r["i"] >= base:
                    base, base_term = r["i"], r["tm"]
        if snap is not None and base == 0:
            # snapshot without any base record (install path)
            base, base_term = snap_index, snap_term
        for i in [i for i in entries if i <= base]:
            del entries[i]
        self._records_since_rewrite = wal_records
        self.recovery = rec
        self._emit_recovery(rec)
        if not have_meta and not entries and snap is None \
                and wal_records == 0 and not m_corrupt and not s_corrupt:
            return None
        return {"term": meta["term"], "voted_for": meta["voted_for"],
                "base": base, "base_term": base_term,
                "snap_index": snap_index, "snap_term": snap_term,
                "snapshot": snap["data"] if snap else None,
                "entries": entries, "recovery": rec}

    @staticmethod
    def _emit_recovery(rec: dict) -> None:
        """Surface recovery outcomes: ops alert on corrupt_frame /
        *_fallback the way the reference alerts on raft-wal repairs."""
        clean = True
        for key in ("torn_tail", "corrupt_frame"):
            if rec[key]:
                telemetry.incr_counter(("raft", "recovery", key),
                                       float(rec[key]))
                clean = False
        for key in ("meta_fallback", "meta_lost", "snap_fallback",
                    "snap_lost"):
            if rec[key]:
                telemetry.incr_counter(("raft", "recovery", key))
                clean = False
        if clean:
            telemetry.incr_counter(("raft", "recovery", "clean"))

    def _replay_wal(self, rec: dict):
        """Yield WAL records, truncating the tail at the first torn or
        corrupt frame.  Truncation never cuts EARLIER than the bad
        frame: every record acked before it survives quarantine."""
        try:
            f = self.io.open_read(self._wal_path)
        except FileNotFoundError:
            return
        good = 0
        reason = None
        with f:
            while True:
                head = f.read(2)
                if len(head) < 2:
                    if head:
                        reason = "torn_tail"
                    break
                if head == WAL_MAGIC:
                    hdr = f.read(8)
                    if len(hdr) < 8:
                        reason = "torn_tail"
                        break
                    ln, crc = struct.unpack(">II", hdr)
                    blob = f.read(ln)
                    if len(blob) < ln:
                        reason = "torn_tail"
                        break
                    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
                        reason = "corrupt_frame"  # rot, not a tear
                        break
                    try:
                        r = json.loads(blob)
                    except ValueError:
                        reason = "corrupt_frame"
                        break
                    if not isinstance(r, dict) or "t" not in r:
                        reason = "corrupt_frame"
                        break
                elif head[0] != 0:
                    # neither v2 magic nor a plausible v1 frame: v1
                    # length prefixes always start 0x00 (payloads are
                    # far below 2^24), so this is a v2 header whose
                    # MAGIC rotted — acked-data corruption, not a tear
                    reason = "corrupt_frame"
                    break
                else:
                    # v1 frame: bare u32 length + JSON payload (no
                    # checksum — the format this PR retired)
                    rest = f.read(2)
                    if len(rest) < 2:
                        reason = "torn_tail"
                        break
                    (ln,) = struct.unpack(">I", head + rest)
                    blob = f.read(ln)
                    if len(blob) < ln:
                        reason = "torn_tail"
                        break
                    try:
                        r = json.loads(blob)
                    except ValueError:
                        # a v1 tear and v1 rot are indistinguishable
                        reason = "torn_tail"
                        break
                    if not isinstance(r, dict) or "t" not in r:
                        reason = "torn_tail"
                        break
                    rec["v1_frames"] += 1
                good = f.tell()
                yield r
        size = self.io.getsize(self._wal_path)
        if good != size:
            rec[reason or "torn_tail"] += 1
            rec["dropped_bytes"] += size - good
            # quarantine in place: everything before the bad frame was
            # fsynced in file order and survives
            self._wal.close()
            f = self.io.open_rw(self._wal_path)
            with f:
                self.io.truncate(f, good)
                self.io.fsync(f)
            self._wal = self.io.open_append(self._wal_path)

    # ------------------------------------------------------------- writes

    @staticmethod
    def _encode_frame(rec: dict) -> bytes:
        """The ONE place the v2 frame encoding lives — _frame and the
        compaction rewrite must never diverge, or a rewrite would
        produce a WAL replay truncates at frame one."""
        blob = json.dumps(rec).encode()
        return WAL_MAGIC + struct.pack(
            ">II", len(blob), zlib.crc32(blob) & 0xFFFFFFFF) + blob

    def _frame(self, rec: dict) -> None:
        # one write() per frame: the torn-write model (and the page
        # cache) tears BETWEEN writes far more often than inside one
        self.io.write(self._wal, self._encode_frame(rec))
        self._dirty = True
        self._records_since_rewrite += 1

    def append(self, idx: int, term: int, cmd: Any,
               noop: bool = False) -> None:
        self._frame({"t": "e", "i": idx, "tm": term, "c": cmd,
                     "n": noop})

    def truncate_from(self, idx: int) -> None:
        """Conflict resolution deleted entries >= idx."""
        self._frame({"t": "trunc", "i": idx})

    def sync(self) -> None:
        """fsync pending WAL records; MUST run before the node
        acknowledges those entries to anyone (append_reply, own
        match-index count)."""
        if not self._dirty:
            return
        self.io.fsync(self._wal)
        self._dirty = False

    def _atomic_checked(self, path: str, obj: Any) -> None:
        """Checked tmp-write + generation rotation + rename + dir
        fsync.  Between the two renames the current file is briefly
        absent; load() falls back to `.prev` through that window AND
        through the corruption a reordering disk can leave behind."""
        blob = _dump_checked(obj)
        f, tmp = self.io.create_tmp(self.dir, ".tmp-")
        try:
            with f:
                self.io.write(f, blob)
                self.io.fsync(f)
            if self.io.exists(path) and self._verify_current(path):
                # rotate ONLY a generation that still passes its
                # checksum: rotating a rotted current file would
                # clobber the one good .prev with garbage right before
                # a crash window could need it (recovery-heal rewrite)
                self.io.replace(path, path + ".prev")
            self.io.replace(tmp, path)
            self.io.fsync_dir(self.dir)
        except BaseException:
            try:
                self.io.unlink(tmp)
            except OSError:
                pass
            raise

    def _verify_current(self, path: str) -> bool:
        try:
            with self.io.open_read(path) as f:
                return _parse_checked(f.read())[1] != "corrupt"
        except OSError:
            return False

    def set_term_vote(self, term: int, voted_for: Optional[str]) -> None:
        """Durable BEFORE any message carrying the new term/vote leaves
        this node (Raft's persistent-state rule)."""
        self._atomic_checked(self._meta_path, {"term": term,
                                               "voted_for": voted_for})

    def save_snapshot(self, snap_index: int, snap_term: int, data: Any,
                      live_entries: Dict[int, Tuple[int, Any, bool]],
                      base: Optional[int] = None,
                      base_term: Optional[int] = None) -> dict:
        """Persist a snapshot and move the log window base (defaults to
        the snapshot index — the InstallSnapshot shape; compaction
        passes a trailing base so the catch-up window survives
        restarts).  Returns {"rewrote": bool} for harnesses that track
        the WAL's physical identity.

        Cheap path: snap.json + one appended base record (two fsyncs).
        The WAL is only REWRITTEN to the live window once it carries
        ~rewrite_threshold records; a rewrite that fails mid-way
        (ENOSPC) is abandoned — the old WAL is still complete, so the
        node keeps appending and retries at the next compaction."""
        if base is None:
            base, base_term = snap_index, snap_term
        self._atomic_checked(self._snap_path,
                             {"index": snap_index, "term": snap_term,
                              "data": data})
        # verify-before-ack: the snapshot is about to anchor recovery,
        # so prove the bytes on disk parse + checksum before the base
        # record makes the log window depend on them
        got, corrupt, used_prev = self._load_checked(self._snap_path)
        if got is None or used_prev or got.get("index") != snap_index:
            raise StorageCorruptionError(
                f"snapshot {snap_index} failed read-back verification")
        self._frame({"t": "base", "i": base, "tm": base_term})
        self.sync()
        if self._records_since_rewrite < self.rewrite_threshold:
            return {"rewrote": False}
        try:
            f, tmp = self.io.create_tmp(self.dir, ".wal-")
        except OSError:
            return {"rewrote": False}
        n = 1
        try:
            with f:
                self.io.write(f, self._encode_frame(
                    {"t": "base", "i": base, "tm": base_term}))
                for i in sorted(live_entries):
                    if i <= base:
                        continue
                    tm, cmd, noop = live_entries[i]
                    self.io.write(f, self._encode_frame(
                        {"t": "e", "i": i, "tm": tm, "c": cmd,
                         "n": noop}))
                    n += 1
                self.io.fsync(f)
        except OSError:
            # disk full mid-rewrite: the old WAL is untouched — drop
            # the partial tmp and carry on, retry next compaction
            try:
                self.io.unlink(tmp)
            except OSError:
                pass
            return {"rewrote": False}
        self._wal.close()
        self.io.replace(tmp, self._wal_path)
        self.io.fsync_dir(self.dir)
        self._wal = self.io.open_append(self._wal_path)
        self._dirty = False
        self._records_since_rewrite = n
        return {"rewrote": True}

    def close(self) -> None:
        self.sync()
        self._wal.close()
        try:
            fcntl.flock(self._lockfd, fcntl.LOCK_UN)
        finally:
            os.close(self._lockfd)

    def abort(self) -> None:
        """kill -9 for tests: drop the fds WITHOUT syncing — pending
        WAL bytes stay wherever the page cache left them, and the
        flock releases so a restarted instance can take the dir."""
        try:
            self._wal.close()
        except OSError:
            pass
        try:
            os.close(self._lockfd)
        except OSError:
            pass
