"""Tick-driven Raft: leader election, log replication, snapshots.

Behavioral equivalent of the hashicorp/raft engine the reference wires in
at agent/consul/server.go:674 (setupRaft) — terms, randomized election
timeouts, AppendEntries consistency checking, quorum commit,
FSM Apply/Snapshot/Restore (agent/consul/fsm/fsm.go:118,145,163), and
InstallSnapshot for lagging followers.  Design departures, deliberate:

  * **Tick-synchronous with an injectable clock.**  The reference absorbs
    wall-clock flakiness with retry loops (sdk/testutil/retry); here time
    is an explicit argument to `tick(now)`, so an in-process multi-server
    cluster (SURVEY.md §4 tier 2) is stepped deterministically — the same
    make-time-explicit stance the device kernels take.
  * **Transport is an interface**; the in-memory one supports partitions
    and message loss for fault-injection tests (the reference's partition
    tests shut sockets down, agent/consul/leader_test.go patterns).
  * raft_multiplier scaling (website docs performance.mdx:33-58) maps to
    scaling `election_timeout` / `heartbeat_interval` in RaftConfig.

Log indexing is 1-based global; `log_base`/`log_base_term` carry the
snapshot horizon so the in-memory window is compacted (the reference's
boltdb log + snapshot store collapse into one object here).
"""

from __future__ import annotations

import bisect
import heapq
import random
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from consul_tpu import locks, telemetry, visibility

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    """Raised on apply() at a non-leader; carries the leader hint the way
    structs.ErrNoLeader / leader-forwarding does (agent/consul/rpc.go:549)."""

    def __init__(self, leader: Optional[str]):
        super().__init__(f"node is not the leader (leader hint: {leader})")
        self.leader = leader


class ChunkLostError(Exception):
    """A chunked command's group was dropped before its final fragment
    applied (out-of-order fragment after truncation, or cap eviction).
    Surfaced through the pending waiter's error slot so the proposer
    retries instead of reading None as a successful apply."""


@dataclass
class RaftConfig:
    election_timeout: Tuple[float, float] = (0.15, 0.30)  # seconds, jittered
    heartbeat_interval: float = 0.05
    snapshot_threshold: int = 1024      # log entries before auto-compaction
    snapshot_trailing: int = 128        # entries kept behind a snapshot
    max_append_entries: int = 64

    @classmethod
    def scaled(cls, raft_multiplier: int = 1) -> "RaftConfig":
        m = max(1, raft_multiplier)
        return cls(election_timeout=(0.15 * m, 0.30 * m),
                   heartbeat_interval=0.05 * m)


class Transport:
    """send() is fire-and-forget; delivery happens into the target inbox."""

    def send(self, target: str, msg: dict) -> None:  # pragma: no cover
        raise NotImplementedError


class InMemTransport(Transport):
    """Process-local message bus with partition + loss injection — the
    freeport/in-process-cluster trick of the reference's tests
    (agent/consul/server_test.go:116-122) without sockets.

    Fault surface (driven by consul_tpu/chaos.py's nemesis): the
    original ad-hoc hooks (`partition`/`heal`/`isolate`, scalar
    `p_loss`) remain, and an optional `injector` generalizes them into
    a schedule: each send consults `injector.on_send(src, dst, msg,
    now)` for a list of delivery delays (empty = dropped, one 0.0 =
    deliver now, several = duplicates, positive = delayed/reordered).
    Delayed frames queue on the transport and flush when the harness
    calls `advance(now)` each tick — delivery stays tick-synchronous
    and fully deterministic under a seeded injector."""

    def __init__(self, seed: int = 0):
        self._nodes: Dict[str, "RaftNode"] = {}     # guarded-by: _lock
        self._lock = locks.make_lock("raft.transport")
        # directed (src, dst) pairs down  # guarded-by: _lock
        self._cut: set = set()
        self.p_loss = 0.0
        self._rng = random.Random(seed)
        self.injector = None            # chaos.LinkInjector-shaped
        self._now = 0.0
        self._seq = 0                   # FIFO tiebreak for equal due times
        # heap of (due, seq, dst, msg)  # guarded-by: _lock
        self._pending: List[tuple] = []
        locks.register_guards(self, self._lock,
                              "_nodes", "_cut", "_pending")

    def register(self, node: "RaftNode") -> None:
        with self._lock:
            self._nodes[node.node_id] = node

    def unregister(self, node_id: str) -> None:
        """A crashed node stops receiving (its queued frames drop with
        it, like frames in a dead process's socket buffer)."""
        with self._lock:
            self._nodes.pop(node_id, None)
            self._pending = [p for p in self._pending if p[2] != node_id]
            heapq.heapify(self._pending)

    def advance(self, now: float) -> None:
        """Deliver every delayed frame that has come due.  The chaos
        harness calls this once per tick step; transports without an
        injector never queue, so plain clusters need not call it."""
        due = []
        with self._lock:
            self._now = now
            while self._pending and self._pending[0][0] <= now:
                _, _, dst, msg = heapq.heappop(self._pending)
                node = self._nodes.get(dst)
                if node is not None:
                    due.append((node, msg))
        for node, msg in due:
            node.deliver(msg)

    def partition(self, a: str, b: str, bidir: bool = True) -> None:
        with self._lock:
            self._cut.add((a, b))
            if bidir:
                self._cut.add((b, a))
            now = self._now
        from consul_tpu import flight
        flight.emit("chaos.fault.injected",
                    labels={"fault": "partition", "target": f"{a}|{b}"},
                    ts=now)

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        with self._lock:
            if a is None:
                self._cut.clear()
            else:
                self._cut.discard((a, b))
                self._cut.discard((b, a))
            now = self._now
        from consul_tpu import flight
        flight.emit("chaos.fault.healed",
                    labels={"fault": "partition",
                            "target": "*" if a is None else f"{a}|{b}"},
                    ts=now)

    def isolate(self, node_id: str) -> None:
        with self._lock:
            for other in self._nodes:
                if other != node_id:
                    self._cut.add((node_id, other))
                    self._cut.add((other, node_id))

    def send(self, target: str, msg: dict) -> None:
        with self._lock:
            if (msg["from"], target) in self._cut:
                return
            if self.p_loss and self._rng.random() < self.p_loss:
                return
            node = self._nodes.get(target)
            if self.injector is not None:
                plan = self.injector.on_send(msg["from"], target, msg,
                                             self._now)
                if plan is not None:
                    deliver_now = False
                    for delay in plan:
                        if delay <= 0.0:
                            deliver_now = True       # at most one copy
                        else:
                            self._seq += 1
                            heapq.heappush(
                                self._pending,
                                (self._now + delay, self._seq, target,
                                 msg))
                    if not deliver_now:
                        return
        if node is not None:
            node.deliver(msg)


@dataclass
class _Entry:
    term: int
    cmd: Any
    noop: bool = False


# oversized commands split into per-entry chunks before the log (the
# reference wraps raft with go-raftchunking at rpc.go:763-792 so one
# huge apply — e.g. a 64-op txn of 512KiB values — can't monopolize an
# AppendEntries round or blow past transport frames)
CHUNK_BYTES = 256 * 1024


def _roughly_big(cmd, budget: int = CHUNK_BYTES) -> bool:
    """Cheap size walk with early exit: small commands (the hot write
    path) must not pay a throwaway json.dumps just to be measured."""
    stack = [cmd]
    total = 0
    while stack:
        o = stack.pop()
        if isinstance(o, str):
            total += len(o)
        elif isinstance(o, (bytes, bytearray)):
            total += len(o)
        elif isinstance(o, dict):
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple)):
            stack.extend(o)
        else:
            total += 8
        if total > budget:
            return True
    return False


@dataclass
class _Pending:
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[Exception] = None
    # proposal wall-stamp: consul.raft.commitTime measures append → FSM
    # apply (the reference's raft commitTime timer)
    t0: float = field(default_factory=_time.perf_counter)


class RaftNode:
    """One Raft participant.  Drive it by calling tick(now) — from a test
    harness with virtual time, or RaftDriver with wall time."""

    def __init__(self, node_id: str, peers: List[str], transport: Transport,
                 apply_fn: Callable[[Any], Any],
                 snapshot_fn: Optional[Callable[[], Any]] = None,
                 restore_fn: Optional[Callable[[Any], None]] = None,
                 config: Optional[RaftConfig] = None, seed: int = 0,
                 store=None):
        self.node_id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.transport = transport
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.cfg = config or RaftConfig()
        # crc32, not hash(): PYTHONHASHSEED salts str hashing per
        # process, which would make election jitter unreproducible
        # across runs no matter what seed the caller fixes
        import zlib
        self._rng = random.Random(
            zlib.crc32(f"{node_id}:{seed}".encode()) & 0xFFFFFFFF)
        # optional DurableLog (consensus/logstore.py): the raft-boltdb
        # role — entries/term/vote/snapshots fsync BEFORE this node
        # acknowledges them (server.go:728)
        self.store = store

        # persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[_Entry] = []
        self.log_base = 0               # entries <= log_base are compacted
        self.log_base_term = 0
        self.snap_index = 0             # FSM state captured through here
        self.snap_term = 0
        self.snapshot_data: Any = None

        # volatile
        self.state = FOLLOWER
        self.commit_index = 0
        # leader-side follower liveness (autopilot server-health input)
        self.last_ack: Dict[str, float] = {}
        self.last_applied = 0
        self.leader_id: Optional[str] = None
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._votes: set = set()        # guarded-by: _lock
        self._prevotes: set = set()     # guarded-by: _lock
        self._last_contact = -1e18      # last valid leader contact (for pre-vote)
        self._election_deadline = 0.0
        self._heartbeat_due = 0.0
        self._needs_bcast = False
        self._inbox: List[dict] = []    # guarded-by: _lock
        # gid -> b64 parts  # guarded-by: _lock
        self._chunk_buf: Dict[str, list] = {}
        self._lock = locks.make_rlock("raft.node")
        # log index -> waiter  # guarded-by: _lock
        self._pending: Dict[int, _Pending] = {}
        # proposer trace ids by log index (LOCAL only — never
        # replicated; trace.py's byte-identical-payload rule).  The
        # apply loop pops them to scope visibility.applying() around
        # the FSM apply so store bumps correlate to the writer's trace.
        self._trace_ids: Dict[int, str] = {}    # guarded-by: _lock
        # (log index, wall ts) of leader-side appends: the feed for the
        # per-peer replication-lag-in-ms gauge — the age of the oldest
        # entry a follower has not acked.  Pruned below min(match).
        self._append_ts: List[Tuple[int, float]] = []   # guarded-by: _lock
        # (log index, receive ts) of FOLLOWER-side appends: the feed
        # for this replica's own staleness bound (readplane max_stale
        # enforcement) — the age of the oldest entry received from the
        # leader but not yet applied.  Pruned below last_applied.
        self._recv_ts: List[Tuple[int, float]] = []     # guarded-by: _lock
        self._self_lag_due = 0.0
        # telemetry staging: helpers that run under self._lock append
        # (kind, name, value) here and tick()/apply_many() flush AFTER
        # releasing it — sink emission (UDP sendto per configured sink)
        # must never serialize raft progress behind syscalls (the same
        # rule catalog/store.py applies to its blocking-query metrics)
        self._metrics_buf: List[tuple] = []     # guarded-by: _lock
        self._leader_observers: List[Callable[[bool], None]] = []
        self.applied_index_log: List[int] = []    # for tests/metrics
        self._first_tick = True
        # optional wakeup hook: drivers park between ticks and a write
        # or inbound frame should not wait out the sleep (the
        # reference's replication goroutines fire on notify; timers
        # still ride the periodic tick)
        self.on_activity: Optional[Callable[[], None]] = None
        locks.register_guards(self, self._lock, "_votes", "_prevotes",
                              "_inbox", "_chunk_buf", "_pending",
                              "_trace_ids", "_append_ts", "_recv_ts",
                              "_metrics_buf")
        # AFTER the volatile block: boot recovery sets last_applied/
        # commit_index to the snapshot horizon and must not be
        # clobbered by the zero-inits above
        if store is not None:
            self._boot_from_store()

    # requires-lock: _lock
    def _boot_from_store(self) -> None:
        """Crash recovery: rebuild term/vote/log/snapshot from disk.
        Entries above the snapshot base stay UNCOMMITTED until a leader
        re-establishes commit_index — standard raft boot."""
        state = self.store.load()
        if state is None:
            return
        self.current_term = state["term"]
        self.voted_for = state["voted_for"]
        self.log_base = state["base"]
        self.log_base_term = state["base_term"]
        # journal what recovery found (staged — flushed with the first
        # tick's metrics; no ts, so the recorder's clock stamps it:
        # deterministic under the nemesis's fixed-clock recorder)
        rec = state.get("recovery") or {}
        self._metrics_buf.append(
            ("e", "raft.recovery.completed",
             {"node": self.node_id,
              "torn_tail": rec.get("torn_tail", 0),
              "corrupt_frame": rec.get("corrupt_frame", 0),
              "meta_fallback": rec.get("meta_fallback", False),
              "snap_fallback": rec.get("snap_fallback", False),
              "snap_lost": rec.get("snap_lost", False),
              "wal_window_dropped": state["base"] > state["snap_index"]},
             None))
        if state["snapshot"] is not None:
            self.snapshot_data = state["snapshot"]
            self.snap_index = state["snap_index"]
            self.snap_term = state["snap_term"]
            self._metrics_buf.append(
                ("e", "raft.snapshot.restored",
                 {"node": self.node_id, "index": state["snap_index"],
                  "term": state["snap_term"]}, None))
            self._unwrap_restore(state["snapshot"])
        if self.log_base > self.snap_index:
            # the WAL window assumes a NEWER snapshot than the one
            # that survived recovery (snap.json fell back a generation
            # or was lost to rot): entries in (snap_index, log_base]
            # are gone, so serving the window would fake applied state
            # with a silent hole — the storage nemesis catches this as
            # a fork.  Drop the window back to the snapshot horizon
            # and heal the disk; the leader's next append fails its
            # consistency check and replication (or InstallSnapshot)
            # repairs the tail.
            self._metrics_buf.append(
                ("c", ("raft", "recovery", "wal_window_dropped"), 1.0))
            self.log_base = self.snap_index
            self.log_base_term = self.snap_term
            state["entries"] = {}
            self.store.truncate_from(self.snap_index + 1)
            self.store.save_snapshot(self.snap_index, self.snap_term,
                                     self.snapshot_data, {})
        # contiguous run from base+1; a gap means the WAL lost frames
        # (shouldn't happen, but a hole must not fake consistency)
        idx = self.log_base
        while (idx + 1) in state["entries"]:
            idx += 1
            term, cmd, noop = state["entries"][idx]
            self.log.append(_Entry(term, cmd, noop))
        # the FSM is restored through snap_index; log entries between
        # log_base and snap_index are the already-applied catch-up
        # window kept for lagging peers
        self.commit_index = max(self.log_base, self.snap_index)
        self.last_applied = self.commit_index

    def _persist_term_vote(self) -> None:
        if self.store is not None:
            self.store.set_term_vote(self.current_term, self.voted_for)

    def _persist_entry(self, idx: int, e: "_Entry") -> None:
        if self.store is not None:
            self.store.append(idx, e.term, e.cmd, e.noop)

    def _persist_sync(self) -> None:
        if self.store is not None:
            self.store.sync()

    # -------------------------------------------------------------- log math

    @property
    def last_log_index(self) -> int:
        return self.log_base + len(self.log)

    @property
    def last_log_term(self) -> int:
        return self.log[-1].term if self.log else self.log_base_term

    def _term_at(self, idx: int) -> Optional[int]:
        if idx == 0:
            return 0
        if idx == self.log_base:
            return self.log_base_term
        off = idx - self.log_base - 1
        if 0 <= off < len(self.log):
            return self.log[off].term
        return None

    def _entries_from(self, idx: int, limit: int) -> List[dict]:
        off = idx - self.log_base - 1
        return [{"term": e.term, "cmd": e.cmd, "noop": e.noop}
                for e in self.log[off:off + limit]]

    # ------------------------------------------------------------ public API

    def deliver(self, msg: dict) -> None:
        with self._lock:
            self._inbox.append(msg)
        cb = self.on_activity
        if cb is not None:
            cb()

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def pending_count(self) -> int:
        """Proposed-but-unapplied entries with live waiters — the
        leader's in-flight apply queue depth, the quantity the
        ApplyGate's queue_full bound admits against (ratelimit.py)."""
        with self._lock:
            return len(self._pending)

    # ------------------------------------------------- replica staleness

    @property
    def known_leader(self) -> bool:
        """Whether this node currently knows of a leader (itself
        included) — the X-Consul-KnownLeader header's source."""
        return self.leader_id is not None

    def last_contact_s(self, now: Optional[float] = None) -> float:
        """Seconds since this node last heard from a valid leader
        (0.0 on the leader itself) — the X-Consul-LastContact header's
        source.  inf before any contact.  Lock-free: scalar reads are
        GIL-atomic and this sits on the stale-read hot path."""
        if self.state == LEADER:
            return 0.0
        now = _time.time() if now is None else now
        lc = self._last_contact
        if lc <= -1e17:
            return float("inf")
        return max(0.0, now - lc)

    def staleness(self, now: Optional[float] = None) -> float:
        """Upper bound, in seconds, on how far this replica's readable
        state may trail an acked write — what ?max_stale is enforced
        against (readplane).  The leader is 0 by definition.  A
        follower's bound is the worse of:

          * time since last leader contact (everything the leader
            acked since then is invisible here), and
          * age of the oldest entry RECEIVED but not yet applied
            (the `_recv_ts` ring, the follower-side sibling of the
            leader's `_append_ts` lag machinery).
        """
        if self.state == LEADER:
            return 0.0
        now = _time.time() if now is None else now
        age = self.last_contact_s(now)
        # oldest received-but-unapplied entry; the ring is pruned
        # below last_applied by the apply loop.  Snapshot the head
        # UNDER the lock: the apply loop prunes it in place (`del
        # rt[:drop]`), so the old lock-free read here raced the prune —
        # the guarded-by sanitizer surfaced exactly this
        with self._lock:
            rt = self._recv_ts[:8]
            la = self.last_applied
        for idx, ts in rt:
            if idx > la:
                age = max(age, now - ts)
                break
        return age

    def _flush_metrics(self) -> None:
        """Emit staged metrics + flight events; call with the raft
        lock RELEASED (sinks may do I/O; flight forwards to the log
        fan-out)."""
        with self._lock:
            if not self._metrics_buf:
                return
            buf, self._metrics_buf = self._metrics_buf, []
        for kind, name, value, *rest in buf:
            if kind == "c":
                telemetry.incr_counter(name, value,
                                       labels=rest[0] if rest else None)
            elif kind == "g":
                telemetry.set_gauge(name, value,
                                    labels=rest[0] if rest else None)
            elif kind == "e":
                # staged flight event: (kind, name, labels, ts) — ts is
                # the raft clock at the transition (virtual under the
                # nemesis, so chaos timelines replay byte-identical).
                # trace_id explicitly empty: the flush may run inside
                # some unrelated traced request, but the transition it
                # reports happened in raft's own time, not that trace
                from consul_tpu import flight
                flight.emit(name, labels=value, ts=rest[0],
                            trace_id="")
            else:
                telemetry.add_sample(name, value,
                                     labels=rest[0] if rest else None)

    def add_leader_observer(self, fn: Callable[[bool], None]) -> None:
        """Mirror of raft's LeaderCh feeding monitorLeadership
        (agent/consul/leader.go:64)."""
        self._leader_observers.append(fn)

    @staticmethod
    def _expand_entries(cmd: Any, noop: bool) -> list:
        """One command -> its log entry payloads (chunked when big)."""
        if noop or cmd is None or not _roughly_big(cmd):
            return [cmd]
        # Only commands the cheap walk flags as large pay the
        # serialization probe; chunked applies are JSON-round-
        # tripped, which matches what the socket transport does to
        # EVERY command anyway (rpc/net.py JSON frames).  Byte-
        # accurate split over the UTF-8 encoding (character counts
        # under-measure non-ASCII by up to 4x).
        import base64 as _b64
        import json as _json
        import uuid as _uuid
        try:
            blob = _json.dumps(cmd).encode()
        except (TypeError, ValueError):
            blob = b""          # non-JSON cmd: in-memory path only
        if len(blob) <= CHUNK_BYTES:
            return [cmd]
        gid = str(_uuid.uuid4())
        parts = [blob[i:i + CHUNK_BYTES]
                 for i in range(0, len(blob), CHUNK_BYTES)]
        return [{"__chunk__": {
            "id": gid, "seq": i, "total": len(parts),
            "data": _b64.b64encode(p).decode()}}
            for i, p in enumerate(parts)]

    def apply(self, cmd: Any, noop: bool = False) -> _Pending:
        """Leader-only append; returns a waiter resolved at FSM apply
        (raftApply — agent/consul/rpc.go:730).

        Entries replicate on the NEXT tick, not the next heartbeat
        (the reference's replication goroutines fire on notify; the
        heartbeat is only the idle keepalive) — waiting out
        heartbeat_interval would put a 50ms floor under every write.
        Deliberately tick-driven rather than sending here: it keeps
        apply() deterministic (no wall-clock branch perturbing seeded
        message traces) and keeps blocking network I/O off the client
        write path (a send to a partitioned peer would otherwise hold
        the raft lock for the full connect timeout).  Concurrent
        appliers batch into the single per-tick append."""
        return self.apply_many([cmd], noop=noop)[0]

    def apply_many(self, cmds: list, noop: bool = False,
                   trace_ids: Optional[list] = None) -> list:
        """Group commit: append a whole batch of commands under ONE
        lock acquisition, one broadcast flag, and (durably) the shared
        per-tick fsync — returning a waiter per command.  This is the
        leader half of quorum-write batching: a forwarding follower
        coalesces its concurrent applies into one apply_batch RPC
        (server.py), and the batch lands here as one raft round.

        `trace_ids` (one per command, or None) correlates each apply
        with its proposing request for commit-to-visibility tracing;
        defaults to the calling thread's current trace (the in-process
        propose path runs on the request thread).  The ids stay LOCAL —
        they ride `_trace_ids`, never the replicated payload."""
        if trace_ids is None:
            from consul_tpu import trace as _trace
            trace_ids = [_trace.current_trace()] * len(cmds)
        batches = [self._expand_entries(c, noop) for c in cmds]
        pends = []
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if not noop:
                # consul.raft.apply: rate of ACCEPTED raft applies
                # (rpc.go:730 raftApply's metric) — counted after the
                # leadership check so a NotLeaderError + retry at the
                # real leader doesn't double-count the write
                self._metrics_buf.append(
                    ("c", ("raft", "apply"), float(len(cmds))))
            append_wall = self._now if self._now is not None \
                else _time.time()
            for bi, entries in enumerate(batches):
                for e_cmd in entries:
                    ent = _Entry(self.current_term, e_cmd, noop)
                    self.log.append(ent)
                    idx = self.last_log_index
                    # WAL append now, fsync deferred to the commit
                    # decision (_advance_commit) — one group-commit
                    # fsync per tick covers every write batched into it
                    self._persist_entry(idx, ent)
                    self._append_ts.append((idx, append_wall))
                    if len(self._append_ts) > 4096:
                        # a permanently-dead peer must not grow the
                        # ring with write volume; the lag head then
                        # clamps to the oldest retained stamp
                        del self._append_ts[:2048]
                # the waiter resolves when the FINAL chunk (or the
                # single entry) applies
                pend = _Pending()
                self._pending[idx] = pend
                pends.append(pend)
                tid = trace_ids[bi] if bi < len(trace_ids) else None
                if tid and not noop:
                    self._trace_ids[idx] = tid
            self.match_index[self.node_id] = self.last_log_index
            self._needs_bcast = True
        self._flush_metrics()
        cb = self.on_activity
        if cb is not None:
            cb()
        return pends

    def barrier(self) -> _Pending:
        """Commit a no-op in the current term — leader barrier before
        serving (establishLeadership, agent/consul/leader.go:306)."""
        return self.apply(None, noop=True)

    # ------------------------------------------------------------------ tick

    _now = None

    def tick(self, now: float) -> None:
        with self._lock:
            self._now = now
            if self._first_tick:
                self._reset_election_timer(now)
                self._first_tick = False
            inbox, self._inbox = self._inbox, []
            for msg in inbox:
                self._handle(msg, now)
            if self.state in (FOLLOWER, CANDIDATE):
                if now >= self._election_deadline:
                    self._start_election(now)
            if self.state == LEADER and (now >= self._heartbeat_due
                                         or self._needs_bcast):
                self._broadcast_append(now)
            self._advance_commit()
            self._apply_committed()
            self._maybe_compact()
            if self.state == FOLLOWER and now >= self._self_lag_due:
                # follower lag self-report at heartbeat cadence: the
                # node's own staleness bound (last-contact age ∨ oldest
                # unapplied age) — what its readplane enforces
                # ?max_stale against and cluster_top renders next to
                # the leader-side per-peer gauges
                self._self_lag_due = now + self.cfg.heartbeat_interval
                lag_s = self.staleness(now)
                if lag_s < 1e12:        # no-contact sentinel: skip
                    self._metrics_buf.append(
                        ("g", ("raft", "replication", "self_lag_ms"),
                         round(lag_s * 1000.0, 3)))
        self._flush_metrics()

    # -------------------------------------------------------------- internal

    def _reset_election_timer(self, now: float) -> None:
        lo, hi = self.cfg.election_timeout
        self._election_deadline = now + self._rng.uniform(lo, hi)

    # requires-lock: _lock
    def _become_follower(self, term: int, now: float) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.current_term:
            self._metrics_buf.append(
                ("e", "raft.term.changed",
                 {"node": self.node_id, "term": term,
                  "from": self.current_term}, now))
            self.current_term = term
            self.voted_for = None
            self._persist_term_vote()
        self._reset_election_timer(now)
        if was_leader:
            self._metrics_buf.append(
                ("e", "raft.leadership.lost",
                 {"node": self.node_id, "term": self.current_term}, now))
            self._fail_pending(NotLeaderError(self.leader_id))
            for fn in self._leader_observers:
                fn(False)

    # requires-lock: _lock
    def _fail_pending(self, err: Exception) -> None:
        for pend in self._pending.values():
            pend.error = err
            pend.event.set()
        self._pending.clear()
        self._trace_ids.clear()

    # requires-lock: _lock
    def _start_election(self, now: float) -> None:
        """Election timeout fired.  Phase 1 is Pre-Vote (Raft thesis §9.6,
        hashicorp/raft PreVote): probe electability WITHOUT bumping our term
        so a partitioned node can't depose a healthy leader on rejoin."""
        self._prevotes = {self.node_id}
        self._reset_election_timer(now)
        for p in self.peers:
            self.transport.send(p, {
                "type": "pre_vote", "from": self.node_id,
                "term": self.current_term + 1,
                "last_log_index": self.last_log_index,
                "last_log_term": self.last_log_term})
        self._maybe_prevote_win(now)

    # requires-lock: _lock
    def _maybe_prevote_win(self, now: float) -> None:
        if self.state == LEADER:
            return
        if len(self._prevotes) * 2 <= len(self.peers) + 1:
            return
        self.state = CANDIDATE
        self._metrics_buf.append(("c", ("raft", "state", "candidate"), 1.0))
        self.current_term += 1
        self._metrics_buf.append(
            ("e", "raft.election.started",
             {"node": self.node_id, "term": self.current_term}, now))
        self.voted_for = self.node_id
        # durable BEFORE any request_vote leaves: a crashed-and-
        # restarted candidate must not double-vote in this term
        self._persist_term_vote()
        self._votes = {self.node_id}
        self._prevotes = set()
        self.leader_id = None
        for p in self.peers:
            self.transport.send(p, {
                "type": "request_vote", "from": self.node_id,
                "term": self.current_term,
                "last_log_index": self.last_log_index,
                "last_log_term": self.last_log_term})
        self._maybe_win(now)

    # requires-lock: _lock
    def _maybe_win(self, now: float) -> None:
        if self.state != CANDIDATE:
            return
        if len(self._votes) * 2 > len(self.peers) + 1:
            self.state = LEADER
            self._metrics_buf.append(("c", ("raft", "state", "leader"),
                                      1.0))
            self._metrics_buf.append(
                ("e", "raft.election.won",
                 {"node": self.node_id, "term": self.current_term}, now))
            self.leader_id = self.node_id
            nxt = self.last_log_index + 1
            self.next_index = {p: nxt for p in self.peers}
            self.match_index = {p: 0 for p in self.peers}
            self.match_index[self.node_id] = self.last_log_index
            # no-op barrier commits this term (Raft §8 / leader.go:306)
            barrier = _Entry(self.current_term, None, True)
            self.log.append(barrier)
            self._persist_entry(self.last_log_index, barrier)
            # fresh leadership = fresh lag stamps: a previous reign's
            # ring may hold indexes that were truncated while we were
            # a follower — appending this term's entries after them
            # would leave the ring unsorted with duplicate indexes and
            # make the bisect in _stage_replication_lag resolve a
            # caught-up peer to a stale pre-deposition timestamp
            self._append_ts.clear()
            self._append_ts.append((self.last_log_index, now))
            self._recv_ts.clear()       # a leader is 0-stale by definition
            self.match_index[self.node_id] = self.last_log_index
            self._heartbeat_due = now
            self._broadcast_append(now)
            for fn in self._leader_observers:
                fn(True)

    # requires-lock: _lock
    def _broadcast_append(self, now: float) -> None:
        self._needs_bcast = False
        self._heartbeat_due = now + self.cfg.heartbeat_interval
        if self.peers and self.last_ack:
            # consul.raft.leader.lastContact: ms since this leader last
            # heard from its median follower (the hashicorp/raft leader
            # lease gauge); sampled at heartbeat cadence, same tick
            # clock as the acks so virtual-time tests stay coherent
            acks = sorted(self.last_ack.get(p, -1e18) for p in self.peers)
            quorum_ack = acks[len(acks) // 2]
            age_ms = max(0.0, (now - quorum_ack) * 1000.0)
            if age_ms < 1e12:         # no contact yet: skip the sentinel
                self._metrics_buf.append(
                    ("g", ("raft", "leader", "lastContact"),
                     round(age_ms, 3)))
        self._stage_replication_lag(now)
        for p in self.peers:
            self._send_append(p)

    # requires-lock: _lock
    def _stage_replication_lag(self, now: float) -> None:
        """Per-peer follower lag at heartbeat cadence, leader-side —
        the reference exposes none of this; the streaming-reads
        redesign (ROADMAP item 2) needs it as an SLI.  Two gauges per
        peer, staged through _metrics_buf like every raft metric:

          consul.raft.replication.lag{peer}     entries the follower
                                                has not acked
          consul.raft.replication.lag_ms{peer}  age of the OLDEST
                                                unacked entry (0 when
                                                caught up)

        Label cardinality is bounded by the peer set.  `_append_ts` is
        pruned below min(match) here — entries every follower acked can
        never be a lag head again."""
        if not self.peers:
            return
        matches = [self.match_index.get(p, 0) for p in self.peers]
        floor = min(matches)
        ts = self._append_ts
        drop = 0
        while drop < len(ts) and ts[drop][0] <= floor:
            drop += 1
        if drop:
            del ts[:drop]
        head = self.last_log_index
        for p, m in zip(self.peers, matches):
            lag = max(0, head - m)
            self._metrics_buf.append(
                ("g", ("raft", "replication", "lag"), float(lag),
                 {"peer": p}))
            if lag == 0:
                lag_ms = 0.0
            else:
                # oldest unacked entry's age (ts is idx-sorted, so
                # bisect, not a scan — this runs every heartbeat); an
                # entry older than the ring reaches back is at least
                # as old as the ring head
                pos = bisect.bisect_right(ts, m, key=lambda e: e[0])
                oldest = ts[pos][1] if pos < len(ts) \
                    else (ts[0][1] if ts else now)
                lag_ms = max(0.0, (now - oldest) * 1000.0)
            self._metrics_buf.append(
                ("g", ("raft", "replication", "lag_ms"),
                 round(lag_ms, 3), {"peer": p}))

    def _send_append(self, peer: str) -> None:
        nxt = self.next_index.get(peer, self.last_log_index + 1)
        if nxt <= self.log_base:
            # peer is behind the snapshot horizon → InstallSnapshot
            self.transport.send(peer, {
                "type": "install_snapshot", "from": self.node_id,
                "term": self.current_term,
                "last_index": self.snap_index, "last_term": self.snap_term,
                "data": self.snapshot_data})
            return
        prev = nxt - 1
        self.transport.send(peer, {
            "type": "append_entries", "from": self.node_id,
            "term": self.current_term,
            "prev_index": prev, "prev_term": self._term_at(prev) or 0,
            "entries": self._entries_from(nxt, self.cfg.max_append_entries),
            "leader_commit": self.commit_index})

    # requires-lock: _lock
    def _handle(self, msg: dict, now: float) -> None:
        t = msg["type"]
        if t == "pre_vote":
            # grant without touching our term: candidate log up-to-date AND
            # we have no live leader (quiet for >= min election timeout)
            up_to_date = (
                msg["last_log_term"] > self.last_log_term
                or (msg["last_log_term"] == self.last_log_term
                    and msg["last_log_index"] >= self.last_log_index))
            quiet = (self.leader_id is None
                     or now - self._last_contact
                     >= self.cfg.election_timeout[0])
            grant = (msg["term"] > self.current_term and up_to_date
                     and quiet and self.state != LEADER)
            self.transport.send(msg["from"], {
                "type": "pre_vote_reply", "from": self.node_id,
                "term": self.current_term, "granted": grant})
            return
        if t == "pre_vote_reply":
            if msg["granted"] and self.state != LEADER:
                self._prevotes.add(msg["from"])
                self._maybe_prevote_win(now)
            return
        if msg.get("term", 0) > self.current_term:
            self._become_follower(msg["term"], now)
        if t == "request_vote":
            self._on_request_vote(msg, now)
        elif t == "vote_reply":
            if (self.state == CANDIDATE and msg["term"] == self.current_term
                    and msg["granted"]):
                self._votes.add(msg["from"])
                self._maybe_win(now)
        elif t == "append_entries":
            self._on_append_entries(msg, now)
        elif t == "append_reply":
            self._on_append_reply(msg)
        elif t == "install_snapshot":
            self._on_install_snapshot(msg, now)
        elif t == "snapshot_reply":
            if self.state == LEADER and msg["term"] == self.current_term:
                self.next_index[msg["from"]] = msg["last_index"] + 1
                self.match_index[msg["from"]] = msg["last_index"]

    def _on_request_vote(self, msg: dict, now: float) -> None:
        grant = False
        if msg["term"] >= self.current_term:
            up_to_date = (
                msg["last_log_term"] > self.last_log_term
                or (msg["last_log_term"] == self.last_log_term
                    and msg["last_log_index"] >= self.last_log_index))
            if up_to_date and self.voted_for in (None, msg["from"]):
                grant = True
                self.voted_for = msg["from"]
                # vote durable BEFORE the reply leaves (Raft
                # persistent-state rule)
                self._persist_term_vote()
                self._reset_election_timer(now)
        self.transport.send(msg["from"], {
            "type": "vote_reply", "from": self.node_id,
            "term": self.current_term, "granted": grant})

    # requires-lock: _lock
    def _on_append_entries(self, msg: dict, now: float) -> None:
        ok = False
        if msg["term"] >= self.current_term:
            if self.state != FOLLOWER or msg["term"] > self.current_term:
                self._become_follower(msg["term"], now)
            self.leader_id = msg["from"]
            self._last_contact = now
            self._reset_election_timer(now)
            prev_term = self._term_at(msg["prev_index"])
            if msg["prev_index"] <= self.log_base:
                # prefix is inside our snapshot — consistent by definition
                prev_term = msg["prev_term"]
            if prev_term == msg["prev_term"]:
                ok = True
                idx = msg["prev_index"]
                for ent in msg["entries"]:
                    idx += 1
                    have = self._term_at(idx)
                    if idx <= self.log_base:
                        continue            # already snapshotted
                    if have is not None and have != ent["term"]:
                        del self.log[idx - self.log_base - 1:]
                        if self.store is not None:
                            self.store.truncate_from(idx)
                        have = None
                    if have is None:
                        e = _Entry(ent["term"], ent["cmd"],
                                   ent.get("noop", False))
                        self.log.append(e)
                        self._persist_entry(idx, e)
                        # receive stamp for the follower's own
                        # staleness bound; capped like _append_ts so a
                        # stalled apply loop cannot grow it unbounded
                        self._recv_ts.append((idx, now))
                        if len(self._recv_ts) > 4096:
                            del self._recv_ts[:2048]
                if msg["leader_commit"] > self.commit_index:
                    self.commit_index = min(msg["leader_commit"],
                                            self.last_log_index)
                # fsync BEFORE the ok reply: the leader counts this
                # follower's match toward quorum on receipt
                self._persist_sync()
        self.transport.send(msg["from"], {
            "type": "append_reply", "from": self.node_id,
            "term": self.current_term, "ok": ok,
            "match_index": (msg["prev_index"] + len(msg["entries"])) if ok
            else 0,
            "hint_index": min(msg["prev_index"], self.last_log_index + 1)
            if not ok else 0})

    def _on_append_reply(self, msg: dict) -> None:
        import time as _time
        if self.state != LEADER or msg["term"] != self.current_term:
            return
        peer = msg["from"]
        # wall-clock ack stamp (autopilot liveness); the tick clock is
        # virtual in tests, so record both when available
        self.last_ack[peer] = self._now if self._now is not None \
            else _time.time()
        if msg["ok"]:
            self.match_index[peer] = max(self.match_index.get(peer, 0),
                                         msg["match_index"])
            self.next_index[peer] = self.match_index[peer] + 1
            behind = self.last_log_index - self.match_index[peer]
            if behind >= self.cfg.max_append_entries:
                # genuine catch-up (restart, slow link): stream full
                # batches without waiting out the tick
                self._send_append(peer)
            elif behind > 0:
                # a small tail that arrived since the last send: fold
                # it into the next tick's single broadcast.  Replying
                # per-ack here caused an append-per-ack ping-pong
                # under concurrent writers (~6 messages per command);
                # group commit batches them at tick cadence instead.
                self._needs_bcast = True
        else:
            self.next_index[peer] = max(1, msg.get("hint_index", 1))
            self._send_append(peer)

    # requires-lock: _lock
    def _on_install_snapshot(self, msg: dict, now: float) -> None:
        if msg["term"] >= self.current_term:
            if self.state != FOLLOWER:
                self._become_follower(msg["term"], now)
            self.leader_id = msg["from"]
            self._last_contact = now
            self._reset_election_timer(now)
            if msg["last_index"] > self.last_applied:
                self._metrics_buf.append(
                    ("e", "raft.snapshot.installed",
                     {"node": self.node_id, "index": msg["last_index"],
                      "term": msg["last_term"]}, now))
                self._unwrap_restore(msg["data"])
                self.snapshot_data = msg["data"]
                self.log_base = msg["last_index"]
                self.log_base_term = msg["last_term"]
                self.snap_index = msg["last_index"]
                self.snap_term = msg["last_term"]
                self.log = []
                self.commit_index = max(self.commit_index, self.log_base)
                self.last_applied = max(self.last_applied, self.log_base)
                # the restored snapshot IS applied state: stale receive
                # stamps below it would fake an unapplied backlog
                self._recv_ts = [p for p in self._recv_ts
                                 if p[0] > self.last_applied]
                if self.store is not None:
                    # durable before the ack: the leader stops
                    # re-sending once it sees last_index.  Journal a
                    # truncation too — stale WAL entries ABOVE the
                    # snapshot (from a deposed leader) must not
                    # resurrect as phantom log on restart
                    self.store.truncate_from(msg["last_index"] + 1)
                    self.store.save_snapshot(
                        msg["last_index"], msg["last_term"],
                        msg["data"], {})
        self.transport.send(msg["from"], {
            "type": "snapshot_reply", "from": self.node_id,
            "term": self.current_term, "last_index": self.last_applied})

    def _advance_commit(self) -> None:
        if self.state != LEADER:
            return
        # group commit: everything appended this tick hits disk in one
        # fsync before the leader's own match counts toward quorum
        self._persist_sync()
        matches = sorted(self.match_index.values(), reverse=True)
        quorum = (len(self.peers) + 1) // 2 + 1
        if len(matches) < quorum:
            return
        candidate = matches[quorum - 1]
        # Raft §5.4.2: only commit entries from the current term by counting
        if (candidate > self.commit_index
                and self._term_at(candidate) == self.current_term):
            self.commit_index = candidate

    # requires-lock: _lock
    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            off = self.last_applied - self.log_base - 1
            if off < 0:
                continue                    # covered by restored snapshot
            ent = self.log[off]
            result = None
            if not ent.noop:
                t0 = _time.perf_counter()
                # commit-to-visibility: the proposer's trace (local
                # propose-time stamp; absent on followers and after a
                # restart) scopes the FSM apply so every store index
                # this command bumps correlates to the writing request
                tid = self._trace_ids.pop(self.last_applied, None)
                if isinstance(ent.cmd, dict) and "__chunk__" in ent.cmd:
                    with visibility.applying(tid):
                        result = self._apply_chunk(ent.cmd["__chunk__"])
                elif isinstance(ent.cmd, dict) \
                        and "__raft_remove_peer__" in ent.cmd:
                    # replicated membership change (simplified joint
                    # consensus: single-server removal, applied by every
                    # node when the entry commits — raft §6)
                    result = self._apply_remove_peer(
                        ent.cmd["__raft_remove_peer__"])
                else:
                    with visibility.applying(tid):
                        result = self.apply_fn(ent.cmd)
                self._metrics_buf.append(
                    ("s", ("raft", "fsm", "apply"),
                     _time.perf_counter() - t0))
            self.applied_index_log.append(self.last_applied)
            # prune the follower receive-stamp ring: applied entries
            # can never be a staleness head again
            rt = self._recv_ts
            if rt and rt[0][0] <= self.last_applied:
                drop = 0
                while drop < len(rt) and rt[drop][0] <= self.last_applied:
                    drop += 1
                del rt[:drop]
            pend = self._pending.pop(self.last_applied, None)
            if pend is not None:
                # append → quorum commit → FSM apply latency, observed
                # only at the proposer (it owns the waiter)
                self._metrics_buf.append(
                    ("s", ("raft", "commitTime"),
                     _time.perf_counter() - pend.t0))
                if isinstance(result, Exception):
                    pend.error = result
                else:
                    pend.result = result
                pend.event.set()

    # requires-lock: _lock
    def _apply_chunk(self, chunk: dict):
        """Reassemble chunked commands in log order; the FULL command
        applies exactly when its final chunk commits (every replica
        sees the identical sequence, so reassembly is deterministic).
        A seq-0 chunk resets its group; abandoned partial groups (a
        deposed leader's truncated tail) are evicted once more than
        _CHUNK_GROUP_CAP groups accumulate — they can never complete,
        and each can hold megabytes."""
        gid = chunk["id"]
        if chunk["seq"] == 0:
            self._chunk_buf[gid] = []
            while len(self._chunk_buf) > self._CHUNK_GROUP_CAP:
                oldest = next(iter(self._chunk_buf))
                if oldest == gid:
                    break
                del self._chunk_buf[oldest]
        buf = self._chunk_buf.setdefault(gid, [])
        final = chunk["seq"] == chunk["total"] - 1
        if chunk["seq"] != len(buf):
            # out-of-order fragment from a truncated group: drop it;
            # the proposer's retry arrives under a FRESH group id.
            # The proposer's waiter sits on the FINAL chunk's index —
            # if that's the fragment we're dropping, it must see an
            # error, not a None-as-success (silently lost ack).
            self._chunk_buf.pop(gid, None)
            return ChunkLostError(
                f"chunk group {gid} dropped at seq {chunk['seq']}"
            ) if final else None
        buf.append(chunk["data"])
        if len(buf) < chunk["total"]:
            return None
        import base64 as _b64
        import json as _json
        self._chunk_buf.pop(gid, None)
        blob = b"".join(_b64.b64decode(p) for p in buf)
        return self.apply_fn(_json.loads(blob.decode()))

    _CHUNK_GROUP_CAP = 8

    # Chunk reassembly state MUST ride snapshots (go-raftchunking
    # stores it in the FSM for the same reason): a snapshot horizon
    # landing mid-group would otherwise make a restored replica drop
    # the group's tail and silently never apply a command every other
    # replica applied.
    # requires-lock: _lock
    def _wrap_snapshot(self):
        return {"__fsm__": self.snapshot_fn(),
                "__chunks__": {k: list(v)
                               for k, v in self._chunk_buf.items()}}

    # requires-lock: _lock
    def _unwrap_restore(self, data) -> None:
        if isinstance(data, dict) and "__fsm__" in data:
            self._chunk_buf = {k: list(v)
                               for k, v in data["__chunks__"].items()}
            if self.restore_fn is not None:
                self.restore_fn(data["__fsm__"])
        else:
            self._chunk_buf = {}
            if self.restore_fn is not None:
                self.restore_fn(data)

    def _apply_remove_peer(self, peer: str) -> dict:
        if peer in self.peers:
            self.peers.remove(peer)
        self.next_index.pop(peer, None)
        self.match_index.pop(peer, None)
        self.last_ack.pop(peer, None)
        return {"removed": peer}

    def remove_peer(self, peer: str):
        """Leader-proposed single-server removal (operator raft
        remove-peer / autopilot dead-server cleanup).  Returns the
        pending apply."""
        return self.apply({"__raft_remove_peer__": peer})

    def _maybe_compact(self) -> None:
        if self.snapshot_fn is None:
            return
        applied_in_log = self.last_applied - self.log_base
        if applied_in_log < self.cfg.snapshot_threshold:
            return
        keep_from = self.last_applied - self.cfg.snapshot_trailing
        if keep_from <= self.log_base:
            return
        self.snapshot_data = self._wrap_snapshot()
        self.snap_index = self.last_applied
        self.snap_term = self._term_at(self.last_applied) or 0
        new_base_term = self._term_at(keep_from) or self.log_base_term
        self.log = self.log[keep_from - self.log_base:]
        self.log_base = keep_from
        self.log_base_term = new_base_term
        if self.store is not None:
            # base trails the snapshot by the catch-up window so a
            # restart can still serve cheap appends to laggards; the
            # store only rewrites the WAL when it holds enough dead
            # records to be worth it (bounded compaction stall)
            live = {self.log_base + 1 + i: (e.term, e.cmd, e.noop)
                    for i, e in enumerate(self.log)}
            self.store.save_snapshot(self.snap_index, self.snap_term,
                                     self.snapshot_data, live,
                                     base=self.log_base,
                                     base_term=self.log_base_term)

    # ------------------------------------------------------------- stats API

    def stats(self) -> dict:
        """operator raft list-peers / autopilot-ish visibility
        (agent/consul/operator_raft_endpoint.go)."""
        with self._lock:
            return {
                "state": self.state, "term": self.current_term,
                "leader": self.leader_id,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "last_log_index": self.last_log_index,
                "log_base": self.log_base,
                "peers": [self.node_id] + list(self.peers),
            }


class RaftDriver:
    """Wall-clock pump for a set of nodes (one thread, like the reference's
    runtime goroutines but centrally owned — lib/routine.Manager stance)."""

    def __init__(self, nodes: List[RaftNode], tick_seconds: float = 0.01):
        self.nodes = nodes
        self.tick_seconds = tick_seconds
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        import time
        self._running = True

        def loop():
            while self._running:
                now = time.time()
                for n in self.nodes:
                    n.tick(now)
                time.sleep(self.tick_seconds)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5.0)
