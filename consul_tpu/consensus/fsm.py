"""FSM: replicated command registry over the StateStore.

The reference's FSM (agent/consul/fsm/fsm.go:118 Apply; command registry
fsm/commands_oss.go:105-134) decodes raft log entries into state-store
mutations.  Same shape here: a command is `{"op": <name>, "args": {...}}`
and every replica applies it to its own StateStore, so stores converge
deterministically.  Anything nondeterministic (uuids, session ids) is
generated at the *proposer* and carried inside the command — the apply
path must be a pure function of (store, cmd).
"""

from __future__ import annotations

from typing import Any, Dict

from consul_tpu.catalog.store import StateStore


class ServerFSM:
    def __init__(self, store: StateStore):
        self.store = store
        self._ops = {
            "kv_set": self._kv_set,
            "kv_delete": self._kv_delete,
            "txn": self._txn,
            "register_node": self._register_node,
            "register_service": self._register_service,
            "register_check": self._register_check,
            "update_check": self._update_check,
            "deregister_node": self._deregister_node,
            "deregister_service": self._deregister_service,
            "deregister_check": self._deregister_check,
            "session_create": self._session_create,
            "session_renew": self._session_renew,
            "session_destroy": self._session_destroy,
            "acl_policy_set": self._acl_policy_set,
            "acl_policy_delete": self._acl_policy_delete,
            "acl_token_set": self._acl_token_set,
            "acl_token_delete": self._acl_token_delete,
            "acl_bootstrap": self._acl_bootstrap,
            "query_set": self._query_set,
            "query_delete": self._query_delete,
            "intention_set": self._intention_set,
            "intention_delete": self._intention_delete,
            "config_entry_set": self._config_entry_set,
            "config_entry_delete": self._config_entry_delete,
            "coordinate_batch_update": self._coordinate_batch_update,
        }

    def apply(self, cmd: Dict[str, Any]) -> Any:
        op = cmd["op"]
        fn = self._ops.get(op)
        if fn is None:
            # unknown command: ignore-but-log stance of the reference's
            # msgTypeMask forward-compat path (fsm.go:93-116 region)
            return {"error": f"unknown op {op}"}
        return fn(**cmd["args"])

    # each handler returns a JSON-able result dict

    def _kv_set(self, key, value, flags=0, cas=None, acquire=None,
                release=None):
        if isinstance(value, str):
            # latin-1 round-trips arbitrary bytes 1:1 (the proposer encodes
            # with latin-1 too); utf-8 would mangle bytes > 0x7F
            value = value.encode("latin-1")
        ok, idx = self.store.kv_set(key, value, flags=flags, cas=cas,
                                    acquire=acquire, release=release)
        return {"ok": ok, "index": idx}

    def _kv_delete(self, key, recurse=False, cas=None):
        ok, idx = self.store.kv_delete(key, recurse=recurse, cas=cas)
        return {"ok": ok, "index": idx}

    def _txn(self, ops):
        for op in ops:
            if isinstance(op.get("value"), str):
                op["value"] = op["value"].encode("latin-1")
        ok, results, idx = self.store.txn(ops)
        safe = [r if not isinstance(r, dict) else
                dict(r, value=(r["value"].decode("latin-1")
                               if isinstance(r.get("value"), bytes) else
                               r.get("value")))
                for r in results]
        return {"ok": ok, "results": safe, "index": idx}

    def _register_node(self, node, address, meta=None, node_id=None):
        return {"index": self.store.register_node(node, address, meta,
                                                  node_id)}

    def _register_service(self, node, service_id, name, port=0, tags=None,
                          meta=None, address="", kind="", proxy=None):
        # kind/proxy carry the mesh shape (connect-proxy sidecars +
        # gateways); absent in older log entries, so they default
        return {"index": self.store.register_service(
            node, service_id, name, port, tags, meta, address,
            kind=kind, proxy=proxy)}

    def _register_check(self, node, check_id, name, status="critical",
                        service_id="", output=""):
        return {"index": self.store.register_check(
            node, check_id, name, status, service_id, output)}

    def _update_check(self, node, check_id, status, output=""):
        try:
            return {"index": self.store.update_check(node, check_id, status,
                                                     output)}
        except KeyError:
            return {"error": "unknown check", "index": self.store.index}

    def _deregister_node(self, node):
        return {"index": self.store.deregister_node(node)}

    def _deregister_service(self, node, service_id):
        return {"index": self.store.deregister_service(node, service_id)}

    def _deregister_check(self, node, check_id):
        return {"index": self.store.deregister_check(node, check_id)}

    def _session_create(self, sid, node, ttl=0.0, behavior="release",
                        lock_delay=15.0, checks=None, now=None):
        try:
            sid, idx = self.store.session_create(
                node, ttl=ttl, behavior=behavior, lock_delay=lock_delay,
                checks=checks, sid=sid, now=now)
            return {"id": sid, "index": idx}
        except KeyError:
            return {"error": "unknown node", "index": self.store.index}

    def _session_renew(self, sid, now=None):
        return {"ok": self.store.session_renew(sid, now=now)}

    def _session_destroy(self, sid, now=None):
        return {"index": self.store.session_destroy(sid, now=now)}

    # ACL commands (the reference's ACL*SetRequestType family,
    # fsm/commands_oss.go:105-134)

    def _acl_policy_set(self, pid, name, rules, description=""):
        try:
            return {"index": self.store.acl_policy_set(pid, name, rules,
                                                       description)}
        except ValueError as e:
            return {"error": str(e), "index": self.store.index}

    def _acl_policy_delete(self, pid):
        return {"index": self.store.acl_policy_delete(pid)}

    def _acl_token_set(self, accessor, secret, policies=None,
                       description="", token_type="client", local=False,
                       service_identities=None, node_identities=None):
        return {"index": self.store.acl_token_set(
            accessor, secret, policies, description, token_type, local,
            service_identities=service_identities,
            node_identities=node_identities)}

    def _acl_token_delete(self, accessor):
        return {"index": self.store.acl_token_delete(accessor)}

    def _query_set(self, qid, query):
        try:
            return {"index": self.store.query_set(qid, query)}
        except ValueError as e:
            return {"error": str(e), "index": self.store.index}

    def _query_delete(self, qid):
        return {"index": self.store.query_delete(qid)}

    def _intention_set(self, iid, source, destination, action,
                       description="", meta=None):
        try:
            return {"index": self.store.intention_set(
                iid, source, destination, action, description, meta)}
        except ValueError as e:
            return {"error": str(e), "index": self.store.index}

    def _intention_delete(self, iid):
        return {"index": self.store.intention_delete(iid)}

    def _config_entry_set(self, kind, name, body):
        try:
            return {"index": self.store.config_entry_set(kind, name,
                                                         body)}
        except ValueError as e:
            return {"error": str(e), "index": self.store.index}

    def _config_entry_delete(self, kind, name):
        return {"index": self.store.config_entry_delete(kind, name)}

    def _coordinate_batch_update(self, updates):
        return {"index": self.store.coordinate_batch_update(updates)}

    def _acl_bootstrap(self, accessor, secret):
        ok, idx = self.store.acl_bootstrap(accessor, secret)
        return {"ok": ok, "index": idx}
