"""Gossip tuning surface and simulation config.

The tunables mirror Consul's `gossip_lan` / `gossip_wan` blocks
(reference: agent/config/default.go:70-84) with the documented defaults
(reference: website/content/docs/agent/options.mdx:1498-1574):

  LAN: gossip 200ms to 3 nodes, probe 1s / timeout 500ms,
       suspicion_mult 4, retransmit_mult 4
  WAN: gossip 500ms to 4 nodes, probe 5s / timeout 3s, suspicion_mult 6

The simulator discretizes time into ticks of one gossip interval; probes
fire every `probe_interval / gossip_interval` ticks.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class GossipConfig:
    """memberlist-shaped failure-detector tuning (all seconds)."""

    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    gossip_interval: float = 0.2
    gossip_nodes: int = 3
    indirect_checks: int = 3
    suspicion_mult: int = 4
    suspicion_max_timeout_mult: int = 6
    retransmit_mult: int = 4
    # piggyback packet capacity (memberlist UDPBufferSize=1400; an encoded
    # suspect/dead message — type byte, node name, incarnation, from — plus
    # compound-message framing is ~40 bytes)
    udp_packet_bytes: int = 1400
    gossip_msg_bytes: int = 40
    # Lifeguard Local Health Awareness: a node's probe interval and
    # timeout stretch by (health score + 1), score in
    # [0, awareness_max_multiplier - 1].  0 disables the component
    # (memberlist AwarenessMaxMultiplier, default 8).
    awareness_max_multiplier: int = 8

    @classmethod
    def lan(cls) -> "GossipConfig":
        return cls()

    @classmethod
    def wan(cls) -> "GossipConfig":
        return cls(
            probe_interval=5.0,
            probe_timeout=3.0,
            gossip_interval=0.5,
            gossip_nodes=4,
            suspicion_mult=6,
        )

    @property
    def probe_period_ticks(self) -> int:
        return max(1, round(self.probe_interval / self.gossip_interval))

    def retransmit_limit(self, n: int) -> int:
        """memberlist's retransmitLimit: mult * ceil(log10(n + 1))."""
        return self.retransmit_mult * max(1, math.ceil(math.log10(n + 1)))

    def suspicion_min_ticks(self, n: int) -> int:
        """Lifeguard min suspicion timeout, in gossip ticks.

        memberlist: suspicionTimeout = mult * max(1, log10(n)) * probe_interval.
        """
        node_scale = max(1.0, math.log10(max(1, n)))
        return max(1, math.ceil(self.suspicion_mult * node_scale * self.probe_period_ticks))

    def suspicion_max_ticks(self, n: int) -> int:
        return self.suspicion_max_timeout_mult * self.suspicion_min_ticks(n)

    def confirm_k(self) -> int:
        """Expected independent suspicion confirmations (Lifeguard)."""
        return max(1, self.suspicion_mult - 2)

    def packet_msgs(self) -> int:
        """Distinct piggybacked gossip messages per UDP packet — the
        per-contact transfer capacity that bounds mass-event
        dissemination (memberlist packs broadcasts into each packet up
        to UDPBufferSize)."""
        return max(1, self.udp_packet_bytes // self.gossip_msg_bytes)


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Simulator sizing + environment model (static; hashable for jit)."""

    n_nodes: int = 1024
    n_initial: int = 0             # members at t=0 (0 = all N; less
                                   # leaves free slots for elastic join)
    rumor_slots: int = 32          # U: max concurrently-active rumors
    alloc_cap: int = 8             # max new rumors allocated per tick per kind
    p_loss: float = 0.01           # per-leg UDP message loss probability
    # locally-degraded nodes (Lifeguard's motivating scenario: a bad
    # NIC/overloaded host causing ITS probes to fail and suspect
    # healthy peers): a deterministic `degraded_frac` of nodes lose
    # each of their OWN legs with `degraded_loss` instead of p_loss
    degraded_frac: float = 0.0
    degraded_loss: float = 0.0
    rtt_base_ms: float = 0.5       # min one-way latency
    rtt_spread_ms: float = 30.0    # scale of the coordinate space (ms)
    coord_dims: int = 2            # ground-truth latency-space dims
    seed: int = 0
    # node-axis shard count the ring-exchange lowering should assume
    # (ops/rolls.py): set to the mesh device count when the pool shards
    # over a jax.sharding.Mesh so cross-shard ring traffic lowers to
    # static collective-permutes instead of a full all-gather of the
    # doubled buffer.  PURE LOWERING HINT — results are bit-identical
    # for any value (tests/test_sharding.py equivalence); 1 = the
    # single-device doubled-buffer fast path.  Must divide n_nodes.
    shard_blocks: int = 1
    # nemesis hooks (consul_tpu/chaos.py): compiles the per-node
    # partition-group and delivery-rate masks into the tick so a
    # host-side fault schedule can evolve them BETWEEN device scans
    # without recompiles.  Off by default: the hot path carries zero
    # extra work unless a chaos run asks for it.
    chaos: bool = False
