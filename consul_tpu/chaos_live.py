"""Live-cluster nemesis: Jepsen the REAL multi-process cluster over
real sockets.

PRs 3–4 proved election safety, durability, and linearizability
against in-memory raft transports and a simulated disk.  This module
puts the actual deployment shape under faults: one
`tools/server_proc.py` PROCESS per member (the reference's `consul
agent -server` topology), raft frames + leader-forwarded writes over
TCP, HTTP serving per node — and a nemesis that can hurt all of it
without root privileges:

  link faults     every inter-server raft/RPC link is routed through a
                  per-directed-link `LinkProxy` (a toxiproxy-style
                  userspace interposer built on the
                  wanfed.MeshGatewayForwarder accept/pump pattern):
                  the nemesis severs, delays, and heals individual
                  links by flipping proxy state — no iptables needed
  process faults  kill -9 + restart on the same --data-dir, SIGSTOP/
                  SIGCONT pauses (the GC-stall analogue), SIGTERM
                  rolling restarts (graceful-shutdown path)
  disk faults     servers started with --storage-faults write their
                  WAL through a chaos.FaultyStorage; SIGUSR1 injects
                  a POWER LOSS (page cache collapses to the durable
                  view, un-fsynced tail torn per the seeded model,
                  process dies hard) before the restart
  gateway faults  mesh-gateway death mid cross-DC forwarding
                  (wanfed.MeshGatewayForwarder killed under traffic)

Client histories are collected over LIVE HTTP by concurrent load
workers; timeouts are classified AMBIGUOUS (the write may have
committed — api.client.ApiTimeoutError), connection-refused DEFINITE
(api.client.ApiConnectionError), and the histories are checked with
the SAME invariant checkers the in-mem nemesis uses:
`chaos.check_linearizable` (Wing & Gong with ambiguous writes),
`chaos.DurabilityChecker` (acked-write presence + pairwise prefix
consistency over ModifyIndex-ordered replica dumps), and
`chaos.ElectionSafetyChecker` fed from each node's
`/v1/agent/events` flight-recorder feed (raft.election.won rows carry
node + term).  Every node's event feed plus the nemesis's own
injection journal merge into ONE seed-stamped cluster timeline
attached to the report.

Determinism: the fault PLAN (kinds, windows, victim draws) comes from
one `random.Random(seed)` consumed in a fixed call order — the report
digest covers the plan, so the same seed reproduces the same fault
timeline (runtime victim *identities* follow roles like "leader",
which depend on live elections; the plan records the draws).

`tools/chaos_live.py` runs the scenario families and emits
CHAOS_r03.json; `chaos_soak --check` runs the bounded live smoke in
tier-1.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from consul_tpu import flight
from consul_tpu.api.client import (
    ApiConnectionError, ApiError, ApiTimeoutError, Client,
)
from consul_tpu.chaos import (
    DurabilityChecker, ElectionSafetyChecker, RegisterHistory,
    check_linearizable, check_stale_routes,
)
# promoted to introspect.py by ISSUE 10; re-exported for the harness
# and its tests (no behavior change)
from consul_tpu.introspect import EventCollector  # noqa: F401
from consul_tpu.wanfed import MeshGatewayForwarder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# hard wall-clock budget for the tier-1 live smoke (chaos_soak --check)
SMOKE_BUDGET_S = 40.0

REG_KEY = "chaos/reg"          # the single linearizability register
DUR_PREFIX = "dur/"            # unique-key durability stream

TIMELINE_TAIL = 25      # events printed next to a violation report


def print_violation_tail(row: dict, stream=None) -> None:
    """A failing report row's violations + the one-line seed
    reproducer + the last-N merged cluster timeline — the single
    renderer every runner gating on live reports shares
    (tools/chaos_live.py, chaos_soak --check)."""
    stream = stream if stream is not None else sys.stderr
    for v in row["violations"]:
        print(f"VIOLATION [{row['scenario']}]: {v}", file=stream)
        print(f"  reproduce: {row['repro']}", file=stream)
    tail = row.get("events", "").splitlines()[-TIMELINE_TAIL:]
    print(f"  cluster timeline (last {len(tail)} events):",
          file=stream)
    for line in tail:
        print(f"    {line}", file=stream)


def _nap(seconds: float) -> None:
    """The harness's ONLY wait primitive: scenario pacing, poll loops,
    and fault windows all sleep here, on nemesis threads — never on a
    server's tick thread (those live in other processes)."""
    # lint: ok=blocking-call (nemesis pacing sleep on harness threads)
    time.sleep(seconds)


def free_ports(n: int) -> List[int]:
    """Ephemeral ports from the OS (momentarily racy but far safer
    than fixed ports: parallel runs cannot collide)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


# ---------------------------------------------------------------------------
# the per-link TCP interposer (toxiproxy role)
# ---------------------------------------------------------------------------


class LinkProxy(MeshGatewayForwarder):
    """One directed inter-server link as a userspace TCP interposer:
    the wanfed.MeshGatewayForwarder accept/pump machinery (one copy of
    the subtle splice/teardown code, shared with the gateway) plus
    nemesis-controlled state through its subclass hooks:

      sever()       close every live splice AND refuse new ones (the
                    dialer sees dead/instantly-closed connections —
                    a hard partition of this one direction)
      heal()        splice again
      set_delay(s)  sleep `s` per forwarded chunk (head-of-line
                    latency, like a congested path)

    Servers are spawned with their peers pointed at THEIR OWN proxy
    set, so each (src → dst) pair is independently controllable
    without root or iptables."""

    def __init__(self, target: Tuple[str, int], name: str = "",
                 host: str = "127.0.0.1"):
        super().__init__(target[0], int(target[1]), host=host)
        self.name = name
        self.delay_s = 0.0
        self._severed = False

    # -------------------------------------------------------------- nemesis

    def sever(self) -> None:
        self._severed = True
        self._close_live()

    def heal(self) -> None:
        self._severed = False

    def set_delay(self, seconds: float) -> None:
        self.delay_s = max(0.0, float(seconds))

    # --------------------------------------------------- forwarder hooks

    def _admit(self) -> bool:
        return not self._severed

    def _pre_forward(self, data: bytes) -> bool:
        if self._severed:
            return False
        if self.delay_s:
            # head-of-line latency injection IS the fault
            # lint: ok=blocking-call (link delay fault on purpose)
            time.sleep(min(self.delay_s, 1.0))
        return True


# ---------------------------------------------------------------------------
# the managed cluster: one server_proc.py per member, proxied links
# ---------------------------------------------------------------------------


class LiveServer:
    """One member: its real RPC/HTTP ports, data-dir, per-server peers
    spec (peer addresses point at THIS server's outgoing LinkProxies),
    and the live process handle across restarts."""

    def __init__(self, name: str, rpc_port: int, http_port: int,
                 data_dir: str, peers_spec: str,
                 storage_faults: Optional[str] = None,
                 cluster_http: Optional[str] = None,
                 rate_limit: Optional[str] = None,
                 dc: Optional[str] = None,
                 wanfed: bool = False,
                 grpc_port: Optional[int] = None,
                 replicate_from: Optional[str] = None,
                 replicate_interval: float = 1.0):
        self.name = name
        self.rpc_port = rpc_port
        self.http_port = http_port
        self.grpc_port = grpc_port
        self.data_dir = data_dir
        self.peers_spec = peers_spec
        self.storage_faults = storage_faults
        self.cluster_http = cluster_http
        self.rate_limit = rate_limit
        self.dc = dc
        self.wanfed = wanfed
        # secondary-DC replication (ISSUE 18): name of the primary DC
        # this server's leader replicates ACL/intention/config state
        # from, through its own ?dc= WAN forward
        self.replicate_from = replicate_from
        self.replicate_interval = replicate_interval
        # dc1=url|url,dc2=... — set by LiveWan AFTER construction
        # (every DC's ports exist before any process spawns)
        self.federation_http: Optional[str] = None
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0
        self.paused = False

    @property
    def http(self) -> str:
        return f"http://127.0.0.1:{self.http_port}"

    @property
    def grpc(self) -> Optional[str]:
        """host:port of the gRPC ADS plane, None when not enabled."""
        if self.grpc_port is None:
            return None
        return f"127.0.0.1:{self.grpc_port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def spawn(self) -> None:
        assert not self.alive(), f"{self.name} already running"
        self.generation += 1
        os.makedirs(self.data_dir, exist_ok=True)
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "server_proc.py"),
               "--node", self.name, "--peers", self.peers_spec,
               "--http-port", str(self.http_port),
               "--data-dir", self.data_dir]
        if self.grpc_port is not None:
            cmd += ["--grpc-port", str(self.grpc_port)]
        if self.storage_faults:
            cmd += ["--storage-faults", self.storage_faults]
        if self.cluster_http:
            cmd += ["--cluster-http", self.cluster_http]
        if self.rate_limit:
            cmd += ["--rate-limit", self.rate_limit]
        if self.dc:
            cmd += ["--dc", self.dc]
        if self.wanfed:
            cmd += ["--wanfed"]
        if self.federation_http:
            cmd += ["--federation-http", self.federation_http]
        if self.replicate_from:
            cmd += ["--replicate-from", self.replicate_from,
                    "--replicate-interval",
                    str(self.replicate_interval)]
        # per-generation log: the post-mortem evidence when a scenario
        # fails (never parsed, only for humans)
        # lint: ok=blocking-call (harness-side log file, not a tick thread)
        log = open(os.path.join(self.data_dir,
                                f"log.gen{self.generation}.txt"), "ab")
        try:
            self.proc = subprocess.Popen(cmd, stdout=log,
                                         stderr=subprocess.STDOUT,
                                         cwd=REPO)
        finally:
            log.close()
        self.paused = False

    # ------------------------------------------------------ process faults

    def kill9(self) -> None:
        """kill -9: no shutdown path runs; the WAL stays wherever the
        last fsync left it, the data-dir flock dies with the pid."""
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def terminate(self, timeout: float = 15.0) -> Optional[int]:
        """SIGTERM graceful shutdown; returns the exit code (0 on a
        clean rolling-restart path) or None if it had to be killed."""
        self.proc.terminate()
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)
            return None

    def power_loss(self) -> int:
        """SIGUSR1 → FaultyStorage.crash() (torn un-fsynced tail) +
        hard exit.  Only valid for --storage-faults servers."""
        assert self.storage_faults, "power_loss needs --storage-faults"
        self.proc.send_signal(signal.SIGUSR1)
        return self.proc.wait(timeout=10)

    def pause(self) -> None:
        """SIGSTOP: the process freezes mid-whatever (GC-stall / VM
        migration analogue).  Its sockets stay open; peers see silence."""
        self.proc.send_signal(signal.SIGSTOP)
        self.paused = True

    def resume(self) -> None:
        self.proc.send_signal(signal.SIGCONT)
        self.paused = False

    def reap(self) -> None:
        if self.proc is None:
            return
        if self.paused:
            try:
                self.proc.send_signal(signal.SIGCONT)
            except OSError:
                pass
        try:
            self.proc.terminate()
            self.proc.wait(timeout=10)
        except Exception:
            try:
                self.proc.kill()
                self.proc.wait(timeout=10)
            except Exception:
                pass


class LiveCluster:
    """N server processes with every inter-server link interposed.

    Server i's --peers entry for peer j points at the (i → j)
    LinkProxy, whose target is j's REAL rpc port; i's own entry is its
    real bind address.  Severing {(i,j), (j,i)} is a full bidirectional
    partition of that pair; clients still reach every node's HTTP
    directly (the classic Jepsen shape: clients can see a minority the
    cluster majority cannot)."""

    def __init__(self, n: int = 3, data_root: str = ".",
                 storage_faults: Optional[str] = None,
                 rate_limit: Optional[str] = None,
                 dc: Optional[str] = None,
                 wanfed: bool = False,
                 grpc: bool = False,
                 replicate_from: Optional[str] = None,
                 replicate_interval: float = 1.0):
        self.n = n
        self.dc = dc
        # one reservation batch held CONCURRENTLY: rpc, http (and grpc
        # when enabled) ports are guaranteed distinct, and the proxies
        # bind their own ephemeral ports while the reservations are
        # still held, so the kernel cannot hand a proxy a reserved
        # server port
        batch = 3 * n if grpc else 2 * n
        socks = [socket.socket() for _ in range(batch)]
        try:
            for s in socks:
                s.bind(("127.0.0.1", 0))
            ports = [s.getsockname()[1] for s in socks]
            rpc, http = ports[:n], ports[n:2 * n]
            grpc_ports = ports[2 * n:] if grpc else [None] * n
            self.proxies: Dict[Tuple[int, int], LinkProxy] = {}
            for i in range(n):
                for j in range(n):
                    if i != j:
                        self.proxies[(i, j)] = LinkProxy(
                            ("127.0.0.1", rpc[j]),
                            name=f"server{i}->server{j}")
        finally:
            for s in socks:
                s.close()
        self.servers: List[LiveServer] = []
        # every member knows the whole fleet's HTTP surface: enables
        # each node's /v1/internal/ui/cluster-metrics federation view
        cluster_http = ",".join(
            f"server{j}=http://127.0.0.1:{http[j]}" for j in range(n))
        for i in range(n):
            parts = []
            for j in range(n):
                if j == i:
                    parts.append(f"server{j}=127.0.0.1:{rpc[j]}")
                else:
                    p = self.proxies[(i, j)]
                    parts.append(f"server{j}={p.host}:{p.port}")
            self.servers.append(LiveServer(
                f"server{i}", rpc[i], http[i],
                os.path.join(data_root, f"server{i}"), ",".join(parts),
                storage_faults=storage_faults,
                cluster_http=cluster_http, rate_limit=rate_limit,
                dc=dc, wanfed=wanfed, grpc_port=grpc_ports[i],
                replicate_from=replicate_from,
                replicate_interval=replicate_interval))

    # ------------------------------------------------------------ lifecycle

    def start(self, ready_timeout: float = 45.0) -> None:
        for p in self.proxies.values():
            p.start()
        try:
            for s in self.servers:
                s.spawn()
            self.wait_ready(ready_timeout)
        except BaseException:
            self.stop()
            raise

    def stop(self) -> None:
        for s in self.servers:
            s.reap()
        for p in self.proxies.values():
            p.stop()

    def wait_ready(self, timeout: float = 45.0) -> None:
        """A write acked through any node means a leader exists and
        the forwarding plane works."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            for s in self.servers:
                try:
                    if self.client(s, timeout=2.0).kv_put(
                            "chaos/ready", b"1"):
                        return
                except (ApiError, OSError):
                    continue
            _nap(0.3)
        raise RuntimeError("live cluster never elected a leader")

    def wait_http(self, i: int, timeout: float = 20.0) -> bool:
        """The node's HTTP surface answers (process rebooted)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self.client(self.servers[i], timeout=1.5).agent_self()
                return True
            except (ApiError, OSError):
                _nap(0.2)
        return False

    # -------------------------------------------------------------- queries

    def client(self, server, timeout: float = 2.5) -> Client:
        if isinstance(server, int):
            server = self.servers[server]
        return Client(server.http, timeout=timeout)

    def alive_ids(self) -> List[int]:
        return [i for i, s in enumerate(self.servers)
                if s.alive() and not s.paused]

    def leader(self, timeout: float = 25.0) -> int:
        """The node whose OWN raft configuration marks itself leader
        (a node's self-claim, exactly what election safety audits)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            for i in self.alive_ids():
                try:
                    cfg, _, _ = self.client(i, timeout=1.5)._call(
                        "GET", "/v1/operator/raft/configuration")
                except (ApiError, OSError):
                    continue
                for row in cfg.get("Servers", []):
                    if row.get("Leader") and row.get("ID") == \
                            f"server{i}":
                        return i
            _nap(0.2)
        raise RuntimeError("no live leader emerged")

    # -------------------------------------------------------------- nemesis

    def sever_node(self, i: int) -> None:
        """Full bidirectional partition of node i from every peer."""
        for (a, b), p in self.proxies.items():
            if a == i or b == i:
                p.sever()

    @staticmethod
    def _directions(i, j, direction):
        """The directed pairs one (i, j, direction) spec names:
        `out` is i→j only (the historical single-proxy default),
        `in` is j→i, `both` is the full bidirectional partition."""
        if direction not in ("out", "in", "both"):
            raise ValueError(f"direction {direction!r} not one of "
                             f"('out', 'in', 'both')")
        pairs = []
        if direction in ("out", "both"):
            pairs.append((i, j))
        if direction in ("in", "both"):
            pairs.append((j, i))
        return pairs

    def sever_link(self, i: int, j: int,
                   direction: str = "out") -> None:
        """Sever the (i, j) link — one-directional by default, so
        asymmetric partitions (i can't reach j but j still reaches i)
        are expressible; direction="both" severs the pair."""
        for pair in self._directions(i, j, direction):
            self.proxies[pair].sever()

    def heal_link(self, i: int, j: int,
                  direction: str = "both") -> None:
        """Heal one link (both directions by default) without
        touching any other fault — the scalpel next to heal()'s
        fix-everything escape hatch."""
        for pair in self._directions(i, j, direction):
            p = self.proxies[pair]
            p.heal()
            p.set_delay(0.0)

    def delay_node(self, i: int, seconds: float) -> None:
        for (a, b), p in self.proxies.items():
            if a == i or b == i:
                p.set_delay(seconds)

    def heal(self) -> None:
        for p in self.proxies.values():
            p.heal()
            p.set_delay(0.0)

    def kill(self, i: int) -> None:
        self.servers[i].kill9()

    def restart(self, i: int) -> None:
        self.servers[i].spawn()


class LiveWan:
    """N federated datacenters, each a REAL LiveCluster, all cross-DC
    traffic through per-DC mesh gateways (ISSUE 15 tentpole d).

    The composition the ROADMAP item-4 chaos families run against:
    every DC is a full multi-process server cluster; each DC is
    fronted by ONE dc-labeled `wanfed.MeshGatewayForwarder` (running
    in THIS process, so its WAN SLIs and wanfed.splice.* events land
    in the harness's telemetry/flight ring); every server in every DC
    learns every REMOTE DC's gateway via replicated federation states
    and forwards ?dc= requests through it (`--wanfed`), and every
    server serves the merged `/v1/internal/ui/federation` view
    (`--federation-http`).  dc1 never holds a direct route to dc2's
    servers — only dc2's gateway is ever dialed."""

    def __init__(self, data_root: str = ".", dcs=("dc1", "dc2"),
                 n: int = 3, rate_limit: Optional[str] = None,
                 replicate: bool = False,
                 replicate_interval: float = 1.0):
        # replicate=True: the FIRST dc is the primary; every other
        # DC's leader runs the secondary replication set against it
        # (ACL tokens/policies, intentions, config entries) through
        # the severable WAN links below
        self.primary_dc = dcs[0]
        self.clusters: Dict[str, LiveCluster] = {
            dc: LiveCluster(n=n, data_root=os.path.join(data_root, dc),
                            dc=dc, wanfed=True, rate_limit=rate_limit,
                            replicate_from=self.primary_dc
                            if replicate and dc != self.primary_dc
                            else None,
                            replicate_interval=replicate_interval)
            for dc in dcs}
        # the federation spec is known before any process spawns
        # (every cluster reserved its HTTP ports at construction)
        fed = ",".join(
            f"{dc}=" + "|".join(s.http for s in c.servers)
            for dc, c in sorted(self.clusters.items()))
        for c in self.clusters.values():
            for s in c.servers:
                s.federation_http = fed
        self.gateways: Dict[str, MeshGatewayForwarder] = {}
        # per-DIRECTION WAN links: (src, dst) → a LinkProxy fronting
        # dst's gateway, advertised only to src's servers — so one
        # direction of the WAN can be severed without touching the
        # other (asymmetric partitions, ISSUE 18)
        self.wan_links: Dict[Tuple[str, str], LinkProxy] = {}

    # ------------------------------------------------------------ lifecycle

    def start(self, ready_timeout: float = 60.0) -> None:
        try:
            for c in self.clusters.values():
                c.start(ready_timeout=ready_timeout)
            for dc, c in sorted(self.clusters.items()):
                gw = MeshGatewayForwarder(
                    "127.0.0.1", c.servers[0].http_port,
                    dc=dc, gw_name=f"{dc}-gw")
                gw.start()
                self.gateways[dc] = gw
            for src in self.clusters:
                for dst, gw in self.gateways.items():
                    if src == dst:
                        continue
                    lp = LinkProxy((gw.host, gw.port),
                                   name=f"{src}->{dst}-wan")
                    lp.start()
                    self.wan_links[(src, dst)] = lp
            self.advertise()
        except BaseException:
            self.stop()
            raise

    def advertise(self) -> None:
        """Plant every remote DC's gateway address in every server's
        federation states (the replicated-federation-state role; each
        store is DC-local, so every server learns it directly).  Each
        src DC is pointed at its OWN (src, dst) wan link in front of
        dst's gateway, so severing that link partitions exactly the
        src→dst direction."""
        for src, cluster in self.clusters.items():
            for dst, gw in self.gateways.items():
                if src == dst:
                    continue
                link = self.wan_links.get((src, dst))
                host, port = (link.host, link.port) \
                    if link is not None else (gw.host, gw.port)
                body = json.dumps({"MeshGateways": [
                    {"address": host, "port": port}]}).encode()
                for s in cluster.servers:
                    req = urllib.request.Request(
                        f"{s.http}/v1/internal/federation-state/{dst}",
                        data=body, method="PUT")
                    urllib.request.urlopen(req, timeout=5.0).read()

    def stop(self) -> None:
        for lp in self.wan_links.values():
            lp.stop()
        self.wan_links = {}
        for gw in self.gateways.values():
            gw.stop()
        self.gateways = {}
        for c in self.clusters.values():
            c.stop()

    # -------------------------------------------------------------- nemesis

    def sever_link(self, a: str, b: str,
                   direction: str = "both") -> None:
        """Sever the WAN between DCs a and b: `out` cuts only a→b
        (a's requests to b fail, b still reaches a — the asymmetric
        partition), `in` cuts b→a, `both` the full partition."""
        for src, dst in LiveCluster._directions(a, b, direction):
            self.wan_links[(src, dst)].sever()

    def heal_link(self, a: str, b: str,
                  direction: str = "both") -> None:
        """Heal one WAN link pair without touching anything else."""
        for src, dst in LiveCluster._directions(a, b, direction):
            lp = self.wan_links[(src, dst)]
            lp.heal()
            lp.set_delay(0.0)

    def heal(self) -> None:
        """The fix-everything escape hatch: every WAN link and every
        intra-DC link healed, every delay cleared."""
        for lp in self.wan_links.values():
            lp.heal()
            lp.set_delay(0.0)
        for c in self.clusters.values():
            c.heal()

    # -------------------------------------------------------------- queries

    def federation_nodes(self) -> Dict[str, Dict[str, str]]:
        """{dc: {node name: url}} — the introspect.federation_view
        input (and the shape --federation-http serializes)."""
        return {dc: {s.name: s.http for s in c.servers}
                for dc, c in self.clusters.items()}


# ---------------------------------------------------------------------------
# the cluster-wide flight-recorder merge — promoted to
# consul_tpu/introspect.py (ISSUE 10: the collector is the federation
# layer's core, not a chaos-only tool); re-exported at the top of this
# module so every harness/test import path keeps working
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# live load: client histories over real HTTP
# ---------------------------------------------------------------------------


class LiveLoad:
    """Concurrent load workers collecting timestamped client
    histories, with the Jepsen outcome trichotomy:

      acked     the server answered 2xx — the op took effect
      ambiguous the client never learned (timeout / reset / mid-apply
                5xx): it MAY have committed; linearizability treats it
                as maybe-anywhere-after-invoke, durability as
                not-required-but-allowed
      definite  connection refused: the op never entered a server —
                discarded from the history

    Two streams: a single register (REG_KEY) for Wing & Gong, and
    unique keys (DUR_PREFIX) for the durability checker.  Workers
    rotate to the next server after any failure, so load finds the
    live majority on its own (what a client-side LB would do)."""

    def __init__(self, cluster: LiveCluster, seed: int,
                 reg_writers: int = 2, readers: int = 1,
                 dur_writers: int = 2, reg_period: float = 0.3,
                 dur_period: float = 0.08,
                 client_timeout: float = 2.5,
                 stale_readers: int = 0,
                 stale_period: float = 0.15):
        self.cluster = cluster
        self.seed = seed
        self.history = RegisterHistory()
        self._hlock = threading.Lock()
        self.acked: List[Tuple[str, str]] = []        # (key, value)
        self.ambiguous: List[Tuple[str, str]] = []
        # "rejected" = explicit server NACKs (429 rate limit / 503
        # queue-full/deadline): definite non-writes, discarded from
        # the history instead of widening the ambiguous set — the
        # Wing & Gong payoff of ISSUE 13's admission control
        self.counts = {"ok": 0, "ambiguous": 0, "refused": 0,
                       "http_error": 0, "rejected": 0}
        self._clock = threading.Lock()
        self.reg_writers = reg_writers
        self.readers = readers
        self.dur_writers = dur_writers
        self.reg_period = reg_period
        self.dur_period = dur_period
        self.client_timeout = client_timeout
        # follower read plane (ISSUE 12): ?stale GETs round-robined
        # over EVERY node's HTTP, outcomes recorded per-op so a
        # scenario can assert "stale reads kept serving through the
        # fault window"; reads enter the history tagged stale=True for
        # the serializable-prefix checker model
        self.stale_readers = stale_readers
        self.stale_period = stale_period
        self.stale_ops: List[dict] = []   # {t, target, ok, lat, err}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        mk = threading.Thread
        for w in range(self.reg_writers):
            self._threads.append(mk(target=self._reg_writer, args=(w,),
                                    name=f"load-w{w}", daemon=True))
        for r in range(self.readers):
            self._threads.append(mk(target=self._reader, args=(r,),
                                    name=f"load-r{r}", daemon=True))
        for d in range(self.dur_writers):
            self._threads.append(mk(target=self._dur_writer, args=(d,),
                                    name=f"load-d{d}", daemon=True))
        for s in range(self.stale_readers):
            self._threads.append(mk(target=self._stale_reader,
                                    args=(s,),
                                    name=f"load-s{s}", daemon=True))
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.client_timeout + 5.0)

    def _count(self, kind: str) -> None:
        with self._clock:
            self.counts[kind] += 1

    # -------------------------------------------------------------- workers

    def _reg_writer(self, wid: int) -> None:
        rng = random.Random((self.seed << 8) ^ wid)
        target = wid % self.cluster.n
        seq = 0
        while not self._stop.is_set():
            val = f"w{wid}.{seq}"
            seq += 1
            with self._hlock:
                op = self.history.invoke("w", val, time.time())
            try:
                self.cluster.client(target,
                                    timeout=self.client_timeout
                                    ).kv_put(REG_KEY, val)
                with self._hlock:
                    self.history.complete(op, time.time())
                self._count("ok")
            except ApiConnectionError:
                # refused: never entered a server — definite failure
                with self._hlock:
                    self.history.discard(op)
                self._count("refused")
                target = (target + 1) % self.cluster.n
            except ApiError as e:
                if getattr(e, "nack", False):
                    # explicit NACK (rate limit / apply admission):
                    # the server proved the write never entered the
                    # log — a definite failure, not an ambiguous op
                    with self._hlock:
                        self.history.discard(op)
                    self._count("rejected")
                else:
                    # timeouts AND other http errors (a 500 can fire
                    # after the entry was proposed) are AMBIGUOUS
                    with self._hlock:
                        self.history.ambiguous(op)
                    self._count("ambiguous" if e.ambiguous else
                                "http_error")
                target = (target + 1) % self.cluster.n
            _nap(self.reg_period * (0.75 + rng.random() * 0.5))

    def _reader(self, rid: int) -> None:
        rng = random.Random((self.seed << 8) ^ (0x5EAD + rid))
        target = (rid + 1) % self.cluster.n
        while not self._stop.is_set():
            with self._hlock:
                op = self.history.invoke("r", None, time.time())
            try:
                row, _ = self.cluster.client(
                    target, timeout=self.client_timeout).kv_get(
                        REG_KEY, consistent=True)
                val = row["Value"].decode() if row else None
                with self._hlock:
                    self.history.complete(op, time.time(), val)
                self._count("ok")
            except ApiError as e:
                # a read that never returned constrains nothing — but
                # the REPORT counters must still classify honestly
                # (ambiguous timeout vs refused vs server error)
                with self._hlock:
                    self.history.discard(op)
                self._count("ambiguous" if e.ambiguous
                            else "refused" if e.code is None
                            else "http_error")
                target = (target + 1) % self.cluster.n
            except OSError:
                # belt-and-braces: nothing should escape the client's
                # taxonomy, but a dead reader thread would silently
                # thin the history
                with self._hlock:
                    self.history.discard(op)
                self._count("refused")
                target = (target + 1) % self.cluster.n
            _nap(self.reg_period * (0.75 + rng.random() * 0.5))

    def _stale_reader(self, rid: int) -> None:
        """?stale GETs round-robined over every node (follower fanout):
        the read plane's promise under test — a follower keeps
        answering from its local replica through leader faults.  Every
        outcome lands in `stale_ops` with its target and latency so
        scenarios can assert zero refusals and bounded latency inside
        a fault window; successful reads join the history tagged
        stale=True (checked against the serializable-prefix model)."""
        rng = random.Random((self.seed << 8) ^ (0x57A1E + rid))
        target = rid % self.cluster.n
        while not self._stop.is_set():
            t = time.time()
            with self._hlock:
                op = self.history.invoke("r", None, t, stale=True)
            row = {"t": t, "target": target, "ok": False,
                   "lat": 0.0, "err": None}
            try:
                got, _ = self.cluster.client(
                    target, timeout=self.client_timeout).kv_get(
                        REG_KEY, stale=True)
                val = got["Value"].decode() if got else None
                row["ok"] = True
                with self._hlock:
                    self.history.complete(op, time.time(), val)
                self._count("ok")
            except ApiError as e:
                with self._hlock:
                    self.history.discard(op)
                kind = ("ambiguous" if e.ambiguous
                        else "refused" if e.code is None
                        else "http_error")
                row["err"] = kind
                self._count(kind)
            except OSError:
                with self._hlock:
                    self.history.discard(op)
                row["err"] = "refused"
                self._count("refused")
            row["lat"] = round(time.time() - t, 4)
            with self._clock:
                self.stale_ops.append(row)
            target = (target + 1) % self.cluster.n
            _nap(self.stale_period * (0.75 + rng.random() * 0.5))

    def _dur_writer(self, wid: int) -> None:
        rng = random.Random((self.seed << 8) ^ (0xD00D + wid))
        target = wid % self.cluster.n
        seq = 0
        while not self._stop.is_set():
            key = f"{DUR_PREFIX}{wid}/{seq:05d}"
            val = f"d{wid}.{seq}"
            seq += 1
            try:
                self.cluster.client(target,
                                    timeout=self.client_timeout
                                    ).kv_put(key, val)
                with self._clock:
                    self.acked.append((key, val))
                self._count("ok")
            except ApiConnectionError:
                self._count("refused")
                target = (target + 1) % self.cluster.n
            except ApiError as e:
                if getattr(e, "nack", False):
                    # definite non-write: not acked, not ambiguous —
                    # the durability checker must not allow it either
                    self._count("rejected")
                else:
                    with self._clock:
                        self.ambiguous.append((key, val))
                    self._count("ambiguous" if e.ambiguous else
                                "http_error")
                target = (target + 1) % self.cluster.n
            _nap(self.dur_period * (0.75 + rng.random() * 0.5))


# ---------------------------------------------------------------------------
# live invariant checks
# ---------------------------------------------------------------------------


def _node_dump(cluster: LiveCluster, i: int) -> Optional[List[dict]]:
    """This node's LOCAL replica view of the durability stream —
    a ?stale read, the read plane's explicit local-replica mode
    (default-consistency reads now leader-forward on followers when
    the fleet HTTP map is configured, which would make every dump the
    LEADER's view and blind the pairwise prefix check)."""
    try:
        return cluster.client(i, timeout=3.0).kv_list(DUR_PREFIX,
                                                      stale=True)
    except (ApiError, OSError):
        return None


def check_live_durability(cluster: LiveCluster,
                          acked: List[Tuple[str, str]],
                          settle_s: float = 20.0) -> Tuple[List[str],
                                                           dict]:
    """Acked-write durability + replica agreement over live replicas.

    Each node's dump, ordered by ModifyIndex, IS its applied sequence
    for the durability stream (unique keys, written once).  Mid-settle
    dumps feed DurabilityChecker.observe (pairwise prefix — a lagging
    replica is a prefix, a fork is a violation); after convergence,
    final_check asserts every acked write present exactly once, in
    commit order, on every live replica."""
    dc = DurabilityChecker()
    live = cluster.alive_ids()
    if not live:
        # nothing to check against (watchdog reaped the fleet / total
        # wipe-out): report it as the violation it is rather than
        # tripping over empty dumps below
        return (["durability: no live replicas to check the acked "
                 "writes against"], {"converged": False, "live": 0})
    # first pass immediately: replicas may still be catching up — the
    # prefix property must hold even mid-replication
    early = {}
    for i in live:
        rows = _node_dump(cluster, i)
        if rows is not None:
            early[f"server{i}"] = [
                r["Value"].decode() for r in
                sorted(rows, key=lambda r: r["ModifyIndex"])]
    dc.observe(early)
    # converge: identical (key → value, index) maps everywhere
    deadline = time.time() + settle_s
    dumps: Dict[str, List[dict]] = {}
    converged = False
    while time.time() < deadline and not converged:
        dumps = {}
        for i in live:
            rows = _node_dump(cluster, i)
            if rows is None:
                break
            dumps[f"server{i}"] = rows
        if len(dumps) == len(live):
            maps = [
                {r["Key"]: (r["Value"], r["ModifyIndex"])
                 for r in rows} for rows in dumps.values()]
            acked_keys = {k for k, _ in acked}
            converged = all(m == maps[0] for m in maps[1:]) and \
                all(acked_keys <= set(m) for m in maps)
        if not converged:
            _nap(0.4)
    violations = list(dc.violations)
    if not converged:
        violations.append(
            f"durability: replicas did not converge on the "
            f"{DUR_PREFIX} stream within {settle_s:.0f}s "
            f"(sizes: { {n: len(r) for n, r in dumps.items()} })")
        return violations, {"converged": False}
    logs = {}
    for name, rows in dumps.items():
        logs[name] = [r["Value"].decode() for r in
                      sorted(rows, key=lambda r: r["ModifyIndex"])]
    # ack order for final_check = commit order (ModifyIndex); an acked
    # key that never made it into any dump stays at the end and is
    # reported missing
    any_rows = next(iter(dumps.values()))
    idx_of = {r["Key"]: r["ModifyIndex"] for r in any_rows}
    dc.acked = [v for k, v in sorted(
        acked, key=lambda kv: idx_of.get(kv[0], float("inf")))]
    dc.observe(logs)
    violations += dc.final_check(logs, sorted(logs))
    return violations, {"converged": True,
                        "replicated_rows": len(any_rows),
                        "acked": len(acked)}


# ---------------------------------------------------------------------------
# scenario harness
# ---------------------------------------------------------------------------


class _Live:
    """Shared scenario frame: cluster + proxies + load + event
    collector + the seeded fault plan, with a hard wall-clock watchdog
    that kills every server process if a scenario wedges (tier-1 must
    never hang behind a stuck election)."""

    def __init__(self, name: str, seed: int, n: int = 3,
                 check: bool = False,
                 storage_faults: Optional[str] = None,
                 budget_s: Optional[float] = None,
                 load_kw: Optional[dict] = None,
                 rate_limit: Optional[str] = None):
        self.name = name
        self.seed = seed
        self.check = check
        self.rng = random.Random(seed)
        self.plan: List[list] = []
        self.injected: List[list] = []
        self.violations: List[str] = []
        self.detail: dict = {}
        self._t0 = time.time()
        self.budget_exceeded = False
        self._tmp = tempfile.TemporaryDirectory(
            prefix=f"chaos-live-{name}-")
        self.recorder = flight.FlightRecorder(clock=time.time,
                                              forward_to_log=False)
        self._flight_cm = flight.use(self.recorder)
        self._flight_cm.__enter__()
        self._closed = False
        try:
            self.cluster = LiveCluster(n=n, data_root=self._tmp.name,
                                       storage_faults=storage_faults,
                                       rate_limit=rate_limit)
            self.collector = EventCollector(self.cluster)
            self.load = LiveLoad(self.cluster, seed,
                                 **(load_kw or {}))
            self._watchdog = None
            if budget_s:
                self._watchdog = threading.Timer(budget_s,
                                                 self._overrun)
                self._watchdog.daemon = True
                self._watchdog.start()
        except BaseException:
            # the recorder swap is process-global: never leave it
            # dangling behind a failed bring-up
            self._flight_cm.__exit__(None, None, None)
            self._tmp.cleanup()
            raise

    def _overrun(self) -> None:
        self.budget_exceeded = True
        for s in self.cluster.servers:
            s.reap()

    # ------------------------------------------------------------- plumbing

    def start(self) -> None:
        self.cluster.start()
        self.collector.start()
        self.load.start()

    def draw(self, label: str, lo: float, hi: float) -> float:
        """One seeded draw, recorded in the plan — the reproducible
        fault timeline is exactly this sequence."""
        v = round(self.rng.uniform(lo, hi), 3)
        self.plan.append([label, v])
        return v

    def pick(self, label: str, k: int) -> int:
        v = self.rng.randrange(k)
        self.plan.append([label, v])
        return v

    def fault(self, kind: str, target: str) -> None:
        self.plan.append(["fault", kind])
        self.injected.append([round(time.time() - self._t0, 2), kind,
                              target])
        flight.emit("chaos.fault.injected",
                    labels={"fault": kind, "target": target})

    def heal_mark(self, target: str = "*") -> None:
        self.plan.append(["heal", target])
        self.injected.append([round(time.time() - self._t0, 2),
                              "heal", target])
        flight.emit("chaos.fault.healed",
                    labels={"fault": "live", "target": target})

    def run_for(self, seconds: float) -> None:
        _nap(seconds)

    # --------------------------------------------------------------- finish

    def finish(self) -> dict:
        self.load.stop()
        # post-fault liveness: the healed cluster must serve a write
        # through EVERY live node (forwarding plane included)
        for i in list(self.cluster.alive_ids()):
            deadline = time.time() + (12.0 if self.check else 15.0)
            okd = False
            while time.time() < deadline:
                try:
                    okd = self.cluster.client(i, timeout=2.5).kv_put(
                        f"chaos/final/{i}", b"ok")
                    if okd:
                        break
                except (ApiError, OSError):
                    _nap(0.3)
            if not okd:
                self.violations.append(
                    f"liveness: post-heal write through server{i} "
                    f"never succeeded")
        # durability: acked unique-key writes present on every replica
        dur_viol, dur_detail = check_live_durability(
            self.cluster, list(self.load.acked))
        self.violations += dur_viol
        # ambiguous writes are allowed-but-not-required; surface how
        # many there were so the report shows the real fault exposure
        dur_detail["ambiguous_writes"] = len(self.load.ambiguous)
        self.detail["durability"] = dur_detail
        # final event sweep AFTER the settle so late elections ride in
        self.collector.stop()
        es = ElectionSafetyChecker()
        for term, node in self.collector.election_wins():
            es.note(term, node)
        self.violations += es.violations
        self.detail["elections"] = {
            t: sorted(n) for t, n in es.leaders_by_term.items()}
        # linearizability of the live register history
        ops = self.load.history.recorded()
        ok, why = check_linearizable(ops)
        if not ok:
            self.violations.append(f"linearizability: {why}")
        self.detail["history"] = dict(self.load.counts,
                                      register_ops=len(ops))
        if self.budget_exceeded:
            self.violations.append(
                f"wall budget exceeded: the scenario overran its "
                f"hard cap and was killed")
        nemesis_rows, _ = self.recorder.read_page(since=0)
        events = self.collector.merged_jsonl(nemesis_rows)
        digest = hashlib.sha256(
            json.dumps(self.plan, sort_keys=True).encode()
        ).hexdigest()[:16]
        return {
            "scenario": self.name, "seed": self.seed,
            "ok": not self.violations, "violations": self.violations,
            "digest": digest, "plan": self.plan,
            "injected": self.injected, "detail": self.detail,
            "repro": f"python tools/chaos_live.py --scenario "
                     f"{self.name} --seed {self.seed}",
            "events": events,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._watchdog is not None:
            self._watchdog.cancel()
        try:
            self.load.stop()
            self.collector.stop()
        except Exception:
            pass
        finally:
            self.cluster.stop()
            self._flight_cm.__exit__(None, None, None)
            try:
                self._tmp.cleanup()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# scenario families
# ---------------------------------------------------------------------------


def live_partition_heal(seed: int, check: bool = False) -> dict:
    """Partition the live leader (both directions of every link via
    the interposers) under load: the majority elects and keeps
    serving, minority writes go ambiguous, heal reconverges, acked
    writes survive, histories linearize."""
    lv = _Live("live_partition_heal", seed, check=check,
               budget_s=90 if check else 240)
    try:
        lv.start()
        lv.run_for(1.5)
        li = lv.cluster.leader()
        window = lv.draw("partition_window", 3.0 if check else 5.0,
                         4.0 if check else 8.0)
        lv.fault("sever", f"server{li}")
        lv.cluster.sever_node(li)
        lv.run_for(window)
        lv.heal_mark(f"server{li}")
        lv.cluster.heal()
        lv.run_for(2.0 if check else 3.0)
        lv.detail["partitioned"] = f"server{li}"
        return lv.finish()
    finally:
        lv.close()


def live_kill_leader_loop(seed: int, check: bool = False) -> dict:
    """kill -9 the leader, restart it on the SAME data-dir, repeat —
    the WAL recovery path under real SIGKILL, with writes in flight.
    The acceptance bar: a restarted leader rejoins with every acked
    write present (live DurabilityChecker green)."""
    lv = _Live("live_kill_leader_loop", seed, check=check,
               budget_s=SMOKE_BUDGET_S if check else 300)
    try:
        lv.start()
        lv.run_for(1.2)
        loops = 1 if check else 3
        for _ in range(loops):
            li = lv.cluster.leader()
            gap = lv.draw("dead_window", 1.0, 1.8)
            lv.fault("kill9", f"server{li}")
            lv.cluster.kill(li)
            lv.run_for(gap)
            lv.fault("restart", f"server{li}")
            lv.cluster.restart(li)
            if not lv.cluster.wait_http(li):
                lv.violations.append(
                    f"server{li} HTTP never came back after restart")
            lv.run_for(1.0 if check else 1.5)
        lv.detail["loops"] = loops
        return lv.finish()
    finally:
        lv.close()


def live_rolling_restart(seed: int, check: bool = False) -> dict:
    """SIGTERM-graceful rolling restart of every member under
    sustained write load (the operator's upgrade path): each exit must
    be clean (code 0 — API stopped, RPC closed, WAL flushed), and no
    acked write may be lost across the roll."""
    lv = _Live("live_rolling_restart", seed, check=check,
               budget_s=120 if check else 300)
    try:
        lv.start()
        lv.run_for(1.2)
        for i in range(lv.cluster.n):
            lv.fault("sigterm", f"server{i}")
            rc = lv.cluster.servers[i].terminate()
            if rc != 0:
                lv.violations.append(
                    f"rolling restart: server{i} graceful shutdown "
                    f"exited {rc!r} (want 0)")
            lv.run_for(lv.draw("down_window", 0.4, 0.9))
            lv.fault("restart", f"server{i}")
            lv.cluster.restart(i)
            if not lv.cluster.wait_http(i):
                lv.violations.append(
                    f"server{i} HTTP never came back after rolling "
                    f"restart")
            lv.run_for(1.0 if check else 1.5)
        return lv.finish()
    finally:
        lv.close()


def live_torn_disk_restart(seed: int, check: bool = False) -> dict:
    """Power loss on a torn disk, live: servers write their WAL
    through a FaultyStorage(torn=True); SIGUSR1 collapses the page
    cache (seeded torn tail on the un-fsynced bytes) and the process
    dies hard; restart on the mangled dir must recover — acked writes
    survive because acks only follow fsync, and replication repairs
    the torn node's tail."""
    lv = _Live("live_torn_disk_restart", seed, check=check,
               storage_faults=f"seed={seed & 0xFFFF},torn=1",
               budget_s=120 if check else 300)
    try:
        lv.start()
        lv.run_for(1.5)
        li = lv.cluster.leader()
        followers = [i for i in range(lv.cluster.n) if i != li]
        victim = followers[lv.pick("follower_pick", len(followers))]
        for tag, node in (("follower", victim), ("leader", None)):
            if node is None:
                node = lv.cluster.leader()
            lv.fault("power_loss", f"server{node}")
            rc = lv.cluster.servers[node].power_loss()
            if rc != 137:
                lv.violations.append(
                    f"power loss on server{node} exited {rc!r} "
                    f"(want 137)")
            lv.run_for(lv.draw(f"{tag}_down", 0.8, 1.5))
            lv.fault("restart", f"server{node}")
            lv.cluster.restart(node)
            if not lv.cluster.wait_http(node):
                lv.violations.append(
                    f"server{node} HTTP never came back after torn "
                    f"restart")
            lv.run_for(1.2 if check else 2.0)
        row = lv.finish()
        # every restart boots through logstore.load() and journals its
        # recovery report; the merged timeline must show them
        recoveries = lv.collector.count("raft.recovery.completed")
        row["detail"]["recovery_events"] = recoveries
        if recoveries < 2:
            row["violations"].append(
                f"torn restart: expected >=2 raft.recovery.completed "
                f"events in the merged timeline, saw {recoveries}")
            row["ok"] = False
        return row
    finally:
        lv.close()


def live_pause_resume(seed: int, check: bool = False) -> dict:
    """SIGSTOP the leader past the election timeout (the GC-stall /
    VM-migration classic): the majority elects a successor while the
    old leader is frozen mid-term; SIGCONT wakes a process that still
    believes it leads — election safety and linearizability must hold
    through the stale-leader window."""
    lv = _Live("live_pause_resume", seed, check=check,
               budget_s=90 if check else 240)
    try:
        lv.start()
        lv.run_for(1.2)
        loops = 1 if check else 2
        for _ in range(loops):
            li = lv.cluster.leader()
            pause = lv.draw("pause_window", 1.8, 2.6)
            lv.fault("sigstop", f"server{li}")
            lv.cluster.servers[li].pause()
            lv.run_for(pause)
            lv.heal_mark(f"server{li}")
            lv.cluster.servers[li].resume()
            lv.run_for(1.5 if check else 2.0)
        lv.detail["loops"] = loops
        return lv.finish()
    finally:
        lv.close()


def live_stale_reads_through_election(seed: int,
                                      check: bool = False) -> dict:
    """The follower read plane under fire (ISSUE 12 acceptance):

      phase 1  kill -9 the leader with stale-read load fanned out over
               every node: ?stale GETs against the SURVIVORS keep
               succeeding through the whole election window — zero
               refusals, latency bounded well under the client timeout
               (a stale read never waits on an election);

      phase 2  fully sever one follower from its peers: its staleness
               bound grows with the partition, so (a) ?max_stale=1s
               reads against it start REJECTING with 500 once its lag
               exceeds the bound (consul.readplane.rejected +
               readplane.rejected flight events in the merged
               timeline), (b) plain ?stale reads against it KEEP
               serving its frozen replica, and (c) ?consistent reads
               against it 500 leaderless once its election timer fires
               and it drops the leader hint.

    The standard checkers still run over everything: stale reads enter
    the history tagged stale=True (serializable-prefix model),
    writes/consistent-reads stay strictly linearizable."""
    lv = _Live("live_stale_reads_through_election", seed, check=check,
               budget_s=120 if check else 300,
               load_kw={"stale_readers": 2})
    try:
        lv.start()
        lv.run_for(1.5)
        # ---- phase 1: leader kill under stale fanout
        li = lv.cluster.leader()
        window = lv.draw("dead_window", 2.0, 2.5 if check else 3.5)
        t_kill = time.time()
        lv.fault("kill9", f"server{li}")
        lv.cluster.kill(li)
        lv.run_for(window)
        t_heal = time.time()
        lv.fault("restart", f"server{li}")
        lv.cluster.restart(li)
        if not lv.cluster.wait_http(li):
            lv.violations.append(
                f"server{li} HTTP never came back after restart")
        lv.run_for(1.5)
        with lv.load._clock:
            rows = [dict(r) for r in lv.load.stale_ops]
        in_window = [r for r in rows
                     if t_kill <= r["t"] <= t_heal
                     and r["target"] != li]
        lv.detail["stale_reads_in_window"] = len(in_window)
        if not in_window:
            lv.violations.append(
                "stale plane: no stale reads landed on survivors "
                "during the leader-dead window (load too thin to "
                "prove anything)")
        failed = [r for r in in_window if not r["ok"]]
        if failed:
            lv.violations.append(
                f"stale plane: {len(failed)}/{len(in_window)} stale "
                f"GETs against SURVIVING followers failed during the "
                f"leader-dead window — the follower read plane must "
                f"keep serving through an election "
                f"(first: {failed[0]})")
        slow = [r for r in in_window
                if r["lat"] > lv.load.client_timeout * 0.8]
        if slow:
            lv.violations.append(
                f"stale plane: {len(slow)} stale GETs took "
                f">{lv.load.client_timeout * 0.8:.1f}s during the "
                f"election — a local replica read must never wait "
                f"out an election")
        # ---- phase 2: severed follower — bounded staleness enforced
        li2 = lv.cluster.leader()
        followers = [i for i in range(lv.cluster.n) if i != li2]
        victim = followers[lv.pick("sever_pick", len(followers))]
        lv.fault("sever", f"server{victim}")
        lv.cluster.sever_node(victim)
        vc = lv.cluster.client(victim, timeout=2.5)
        # (a) max_stale rejects fire once lag exceeds the bound
        deadline = time.time() + 15.0
        saw_reject = False
        while time.time() < deadline and not saw_reject:
            try:
                vc.kv_get(REG_KEY, max_stale="1s")
            except ApiError as e:
                # the reject is discriminable now (ISSUE 13): 503 +
                # X-Consul-Reason: max-stale, not a bare 500 — assert
                # on the machine-readable contract
                if e.code == 503 and \
                        getattr(e, "reason", None) == "max-stale":
                    saw_reject = True
                    break
            except OSError:
                pass
            _nap(0.3)
        if not saw_reject:
            lv.violations.append(
                "stale plane: ?max_stale=1s against a follower "
                "severed >15s never rejected — the lag bound is not "
                "enforced")
        # (b) plain ?stale keeps serving the frozen replica
        stale_ok = False
        try:
            vc.kv_get(REG_KEY, stale=True)
            stale_ok = True
        except (ApiError, OSError):
            pass
        if not stale_ok:
            lv.violations.append(
                "stale plane: plain ?stale against the severed "
                "follower failed — unbounded stale reads must keep "
                "serving the local replica")
        # (c) ?consistent 500s leaderless on the severed follower
        deadline = time.time() + 15.0
        consistent_500 = False
        while time.time() < deadline and not consistent_500:
            try:
                vc.kv_get(REG_KEY, consistent=True)
            except ApiError as e:
                if e.code is not None and e.code >= 500:
                    consistent_500 = True
                    break
            except OSError:
                pass
            _nap(0.3)
        if not consistent_500:
            lv.violations.append(
                "stale plane: ?consistent against the leaderless "
                "severed follower never 500ed — it must fail loud, "
                "not serve stale data")
        lv.heal_mark(f"server{victim}")
        lv.cluster.heal()
        lv.run_for(2.0 if check else 3.0)
        lv.detail["phase2"] = {"severed": f"server{victim}",
                               "max_stale_reject": saw_reject,
                               "stale_served": stale_ok,
                               "consistent_500": consistent_500}
        row = lv.finish()
        # the merged cluster timeline must carry the reject events —
        # the flight-recorder proof the rejects actually fired where
        # they were injected
        rejects = lv.collector.count("readplane.rejected")
        row["detail"]["readplane_rejected_events"] = rejects
        if saw_reject and rejects < 1:
            row["violations"].append(
                "stale plane: max_stale rejects observed over HTTP "
                "but no readplane.rejected event reached the merged "
                "flight timeline")
            row["ok"] = False
        return row
    finally:
        lv.close()


def live_overload_shed(seed: int, check: bool = False) -> dict:
    """The overload survival plane under a real burst (ISSUE 13): a
    3-proc cluster with ENFORCING ingress limits takes a write burst
    far past its configured rate.

      shed fast     a healthy fraction of burst writes must come back
                    429 + Retry-After (the limiter fired), and every
                    429 must land well under the client timeout — a
                    shed that is slower than service is not a shed;

      shed true     429 is a NACK: burst writes use unique keys, and
                    after the burst NO rejected key may exist on any
                    replica — a "rejected" write that committed would
                    be the limiter lying about non-commitment;

      serve through the background LiveLoad keeps writing under the
                    limit through the burst, and the standard checkers
                    (durability, linearizability, election safety)
                    stay green — shedding the excess must protect the
                    admitted traffic, not corrupt it."""
    lv = _Live("live_overload_shed", seed, check=check,
               budget_s=SMOKE_BUDGET_S if check else 180,
               rate_limit="mode=enforcing,write_rate=60,"
                          "write_burst=90,read_rate=800,"
                          "read_burst=1600",
               # trickle load well under the 60/s write budget
               load_kw={"reg_writers": 1, "dur_writers": 1,
                        "readers": 1, "reg_period": 0.25,
                        "dur_period": 0.15})
    try:
        lv.start()
        lv.run_for(1.0)
        target = lv.pick("burst_target", lv.cluster.n)
        window = lv.draw("burst_window", 2.5, 3.0 if check else 5.0)
        lv.fault("overload_burst", f"server{target}")
        stop_at = time.time() + window
        outcomes: List[dict] = []
        olock = threading.Lock()

        def burster(bid: int) -> None:
            c = lv.cluster.client(target, timeout=3.0)
            seq = 0
            while time.time() < stop_at:
                key = f"burst/{bid}/{seq:05d}"
                seq += 1
                t0 = time.time()
                row = {"key": key, "outcome": "ok",
                       "lat": 0.0, "retry_after": None}
                try:
                    c.kv_put(key, b"x")
                except ApiError as e:
                    row["outcome"] = "rate_limited" \
                        if getattr(e, "nack", False) else (
                            "ambiguous" if e.ambiguous else "error")
                    row["retry_after"] = getattr(e, "retry_after",
                                                 None)
                except OSError:
                    row["outcome"] = "refused"
                row["lat"] = round(time.time() - t0, 4)
                with olock:
                    outcomes.append(row)

        bursters = [threading.Thread(target=burster, args=(b,),
                                     daemon=True) for b in range(4)]
        for t in bursters:
            t.start()
        for t in bursters:
            t.join(timeout=window + 10.0)
        lv.heal_mark(f"server{target}")
        lv.run_for(1.5)
        shed = [o for o in outcomes if o["outcome"] == "rate_limited"]
        okd = [o for o in outcomes if o["outcome"] == "ok"]
        lv.detail["burst"] = {
            "ops": len(outcomes), "ok": len(okd), "shed": len(shed),
            "max_shed_lat_s": round(
                max((o["lat"] for o in shed), default=0.0), 3)}
        if not shed:
            lv.violations.append(
                f"overload: a {len(outcomes)}-op burst against a "
                f"60/s enforcing limiter produced ZERO 429s — "
                f"nothing shed")
        slow_sheds = [o for o in shed if o["lat"] > 0.5]
        if slow_sheds:
            lv.violations.append(
                f"overload: {len(slow_sheds)} 429s took >0.5s — the "
                f"shed path must be faster than service, not slower")
        missing_hint = [o for o in shed if o["retry_after"] is None]
        if missing_hint:
            lv.violations.append(
                f"overload: {len(missing_hint)} 429s arrived without "
                f"a Retry-After hint")
        # NACK truthfulness: no rejected key may exist anywhere —
        # checked over ?stale local-replica dumps on every node
        leaked = []
        shed_keys = {o["key"] for o in shed}
        for i in lv.cluster.alive_ids():
            try:
                rows = lv.cluster.client(i, timeout=3.0).kv_list(
                    "burst/", stale=True)
            except (ApiError, OSError):
                continue
            leaked += [r["Key"] for r in rows if r["Key"] in shed_keys]
        if leaked:
            lv.violations.append(
                f"overload: {len(set(leaked))} rate-LIMITED writes "
                f"exist on replicas ({sorted(set(leaked))[:3]}...) — "
                f"a 429 must prove non-commitment")
        return lv.finish()
    finally:
        lv.close()


def live_gateway_loss(seed: int, check: bool = False) -> dict:
    """Mesh-gateway death during cross-DC forwarding: dc1 reaches dc2
    ONLY through dc2's gateway (wanfed); the nemesis kills the gateway
    mid-transfer.  Cross-DC requests must fail FAST and DEFINITELY
    (bounded latency, no hangs), the forwarder must not leak pump
    threads, and a replacement gateway (new federation state) restores
    service."""
    from consul_tpu.agent import Agent
    from consul_tpu.config import GossipConfig, SimConfig

    rng = random.Random(seed)
    plan: List[list] = []
    violations: List[str] = []
    detail: dict = {}
    recorder = flight.FlightRecorder(clock=time.time,
                                     forward_to_log=False)
    t0 = time.time()
    injected: List[list] = []

    def fault(kind, target):
        plan.append(["fault", kind])
        injected.append([round(time.time() - t0, 2), kind, target])
        flight.emit("chaos.fault.injected",
                    labels={"fault": kind, "target": target})

    a1 = a2 = gw = gw2 = None
    outcomes: List[dict] = []
    stop = threading.Event()

    def read_dc2(client, timeout=4.0):
        t = time.time()
        try:
            client._call("GET", "/v1/kv/gw/reg", {"dc": "dc2"},
                         timeout=timeout)
            return {"ok": True, "lat": time.time() - t}
        except ApiError as e:
            return {"ok": False, "lat": time.time() - t,
                    "ambiguous": e.ambiguous}

    with flight.use(recorder):
        try:
            a1 = Agent(GossipConfig.lan(),
                       SimConfig(n_nodes=8, rumor_slots=8, p_loss=0.0,
                                 seed=(seed & 0xFF) | 1),
                       node_name="dc1-n0", dc="dc1")
            a1.start(tick_seconds=0.0, reconcile_interval=0.5)
            a2 = Agent(GossipConfig.lan(),
                       SimConfig(n_nodes=8, rumor_slots=8, p_loss=0.0,
                                 seed=(seed & 0xFF) | 2),
                       node_name="dc2-n0", dc="dc2")
            a2.start(tick_seconds=0.0, reconcile_interval=0.5)
            gw = MeshGatewayForwarder("127.0.0.1", a2.api.port)
            gw.start()
            a1.store.federation_state_set(
                "dc2", [{"address": gw.host, "port": gw.port}])
            a1.api.wan_fed_via_gateways = True
            Client(a2.api.address).kv_put("gw/reg", b"v0")
            c1 = Client(a1.api.address, timeout=6.0)

            def loader():
                while not stop.is_set():
                    outcomes.append(read_dc2(c1))
                    _nap(0.1)

            lt = threading.Thread(target=loader, daemon=True)
            lt.start()
            # healthy phase: forwarding works through the gateway
            _nap(1.2)
            healthy = [o for o in outcomes if o["ok"]]
            if not healthy:
                violations.append(
                    "gateway: no successful cross-DC read before the "
                    "fault")
            # the kill: abrupt, mid-traffic
            fault("gateway_kill", "dc2-gateway")
            gw.stop()
            loss_window = round(rng.uniform(1.5, 2.5), 3)
            plan.append(["loss_window", loss_window])
            n_before = len(outcomes)
            _nap(loss_window)
            lost = outcomes[n_before:]
            # fail FAST means well under the 4 s client timeout: an op
            # that rides the timeout bound was hanging, not failing
            slow = [o for o in lost if o["lat"] > 3.0]
            if slow:
                violations.append(
                    f"gateway loss: {len(slow)} cross-DC requests "
                    f"took >3s against a dead gateway (must fail "
                    f"fast, not hang into the client timeout)")
            if any(o["ok"] for o in lost):
                violations.append(
                    "gateway loss: a cross-DC read SUCCEEDED with the "
                    "only gateway dead")
            leaked = [t for t in gw._pumps if t.is_alive()]
            if leaked:
                violations.append(
                    f"gateway loss: {len(leaked)} pump threads "
                    f"survived stop()")
            # heal: a replacement gateway, re-advertised
            gw2 = MeshGatewayForwarder("127.0.0.1", a2.api.port)
            gw2.start()
            a1.store.federation_state_set(
                "dc2", [{"address": gw2.host, "port": gw2.port}])
            plan.append(["heal", "gateway"])
            injected.append([round(time.time() - t0, 2), "heal",
                             "dc2-gateway"])
            flight.emit("chaos.fault.healed",
                        labels={"fault": "gateway",
                                "target": "dc2-gateway"})
            deadline = time.time() + 10.0
            recovered = False
            while time.time() < deadline and not recovered:
                recovered = read_dc2(c1)["ok"]
                if not recovered:
                    _nap(0.3)
            if not recovered:
                violations.append(
                    "gateway heal: cross-DC reads never recovered "
                    "through the replacement gateway")
            stop.set()
            lt.join(timeout=10.0)
            detail.update({
                "ops": len(outcomes),
                "healthy_before": len(healthy),
                "failed_during_loss": sum(1 for o in lost
                                          if not o["ok"]),
                "max_latency_s": round(
                    max((o["lat"] for o in outcomes), default=0.0),
                    2),
                "recovered": recovered})
        finally:
            stop.set()
            for g in (gw, gw2):
                if g is not None:
                    g.stop()
            for a in (a1, a2):
                if a is not None:
                    a.stop()
    rows, _ = recorder.read_page(since=0)
    events = "\n".join(
        json.dumps({"ts": round(r["ts"], 3), "node": "nemesis",
                    "name": r["name"], "labels": r["labels"]},
                   sort_keys=True) for r in rows)
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()).hexdigest()[:16]
    return {"scenario": "live_gateway_loss", "seed": seed,
            "ok": not violations, "violations": violations,
            "digest": digest, "plan": plan, "injected": injected,
            "detail": detail,
            "repro": f"python tools/chaos_live.py --scenario "
                     f"live_gateway_loss --seed {seed}",
            "events": events}


def live_wan_partition(seed: int, check: bool = False) -> dict:
    """WAN partition under live replication (ISSUE 18 tentpole a+b):
    a real two-DC LiveWan with dc2's leader replicating ACL tokens/
    policies, intentions, and config entries from dc1 through the
    severable per-direction WAN links.  The nemesis cuts ONLY the
    dc2→dc1 direction (asymmetric partition): dc2's cross-DC requests
    must fail fast and definitely while dc1→dc2 keeps working, the
    replication divergence checker must report NONZERO divergence for
    payloads written in dc1 during the cut, `federation_view` must
    render the diverged DC as rows (with its lag) rather than dropping
    it, and after `heal_link` everything must converge back to zero
    divergence within the SLO, with the diverged→converged flight
    transitions journaled on dc2's leader."""
    from consul_tpu.acl.replication import (AclReplicator,
                                            ConfigEntryReplicator,
                                            IntentionReplicator,
                                            RemoteDcStore)

    rng = random.Random(seed)
    plan: List[list] = []
    violations: List[str] = []
    detail: dict = {}
    injected: List[list] = []
    recorder = flight.FlightRecorder(clock=time.time,
                                     forward_to_log=False)
    t0 = time.time()

    def fault(kind, target):
        plan.append(["fault", kind])
        injected.append([round(time.time() - t0, 2), kind, target])
        flight.emit("chaos.fault.injected",
                    labels={"fault": kind, "target": target})

    RECOVERY_SLO_S = 5.0      # post-heal cross-DC write must land
    CONVERGE_S = 25.0         # replication must reconverge by here

    def cross_dc(client, dc, key, timeout=4.0):
        t = time.time()
        try:
            client._call("PUT", f"/v1/kv/{key}", {"dc": dc},
                         body=b"v", timeout=timeout)
            return {"ok": True, "lat": time.time() - t}
        except (ApiError, OSError) as e:
            return {"ok": False, "lat": time.time() - t,
                    "ambiguous": getattr(e, "ambiguous", True)}

    def rep_statuses(cluster):
        """The diverged/lag rows off whichever node is running the
        replication set (the leader's rounds advance; followers idle)."""
        best = []
        for i in cluster.alive_ids():
            try:
                out, _, _ = cluster.client(i, timeout=2.0)._call(
                    "GET", "/v1/internal/ui/replication")
            except (ApiError, OSError):
                continue
            rows = out.get("replicators") or []
            if sum(r.get("Rounds", 0) for r in rows) > \
                    sum(r.get("Rounds", 0) for r in best):
                best = rows
        return {r["ReplicationType"]: r for r in best}

    def harness_checkers(wan):
        """Harness-side divergence checkers over BOTH fronts directly
        (localhost, never the severed WAN path): the independent
        verdict the in-cluster checker is judged against."""
        prim = lambda: RemoteDcStore(  # noqa: E731
            wan.clusters["dc1"].client(0, timeout=3.0), "dc1")
        sec = lambda: RemoteDcStore(  # noqa: E731
            wan.clusters["dc2"].client(0, timeout=3.0), "dc2")
        return [AclReplicator(prim(), sec()),
                IntentionReplicator(prim(), sec()),
                ConfigEntryReplicator(prim(), sec())]

    wan = None
    tmp = tempfile.TemporaryDirectory(prefix="chaos-live-wan-")
    with flight.use(recorder):
        try:
            wan = LiveWan(data_root=tmp.name, replicate=True,
                          replicate_interval=0.5)
            wan.start()
            dc1, dc2 = wan.clusters["dc1"], wan.clusters["dc2"]
            lead1, lead2 = dc1.leader(), dc2.leader()
            c1 = dc1.client(lead1, timeout=6.0)
            c2 = dc2.client(lead2, timeout=6.0)
            checkers = harness_checkers(wan)

            # ---------------- phase 1: healthy — seed + converge
            pol = c1.acl_policy_create(
                "wan-base", 'key_prefix "" { policy = "read" }')
            c1.acl_token_create([pol["Name"]],
                                description="wan-base-token")
            c1.intention_create("web", "db", "allow")
            c1.config_write({"Kind": "service-resolver",
                             "Name": "db"})
            deadline = time.time() + 30.0
            converged = False
            while time.time() < deadline and not converged:
                converged = all(not ck.check_divergence()["diverged"]
                                for ck in checkers)
                if not converged:
                    _nap(0.5)
            if not converged:
                violations.append(
                    "replication: secondary never converged on the "
                    "seed payloads before the fault")
            base = cross_dc(c2, "dc1", "wan/base")
            if not base["ok"] or base["lat"] > RECOVERY_SLO_S:
                violations.append(
                    f"baseline cross-DC write dc2→dc1 not within SLO "
                    f"({base})")

            # ---------------- phase 2: asymmetric partition
            fault("wan_sever", "dc2->dc1")
            wan.sever_link("dc2", "dc1", direction="out")
            # divergence fuel: new payloads land in the primary while
            # the secondary cannot list it
            pol2 = c1.acl_policy_create(
                "wan-part", 'key_prefix "part/" { policy = "write" }')
            c1.acl_token_create([pol2["Name"]],
                                description="wan-part-token")
            c1.intention_create("web", "cache", "deny")
            c1.config_write({"Kind": "service-resolver",
                             "Name": "cache"})
            part_window = round(rng.uniform(4.0, 6.0), 3)
            plan.append(["part_window", part_window])
            _nap(part_window)
            # asymmetry: dc1→dc2 must still work...
            fwd = cross_dc(c1, "dc2", "wan/asym")
            if not fwd["ok"]:
                violations.append(
                    f"asymmetric partition: dc1→dc2 write failed with "
                    f"only dc2→dc1 severed ({fwd})")
            # ...while dc2→dc1 fails FAST (bounded, no hang into the
            # client timeout)
            cut = cross_dc(c2, "dc1", "wan/cut")
            if cut["ok"]:
                violations.append(
                    "partition: a dc2→dc1 write SUCCEEDED across the "
                    "severed direction")
            elif cut["lat"] > 3.0:
                violations.append(
                    f"partition: dc2→dc1 failed in {cut['lat']:.1f}s "
                    f"— must fail fast, not hang")
            # the harness checker proves NONZERO divergence
            div = {type(ck).__name__: ck.check_divergence()
                   for ck in checkers}
            diverged_types = [k for k, v in div.items()
                              if v["diverged"]]
            if not diverged_types:
                violations.append(
                    "divergence: no payload class diverged although "
                    "writes landed in dc1 behind a severed link")
            # the IN-CLUSTER checker on dc2's leader must agree + lag
            stats = rep_statuses(dc2)
            in_cluster = [t for t, r in stats.items()
                          if r.get("Diverged")]
            if not in_cluster:
                violations.append(
                    f"divergence: dc2's own replication status shows "
                    f"nothing diverged during the partition "
                    f"({sorted(stats)})")
            max_lag = max((r.get("LagSeconds", 0.0)
                           for r in stats.values()), default=0.0)
            if max_lag <= 0.0:
                violations.append(
                    "divergence: replication lag stayed zero through "
                    "the partition")
            # federation_view renders the diverged DC as ROWS with its
            # lag — never an absence (scraped over localhost, so the
            # WAN cut cannot hide a DC from the operator)
            try:
                fed, _, _ = c1._call("GET",
                                     "/v1/internal/ui/federation")
            except (ApiError, OSError) as e:
                fed = None
                violations.append(f"federation view unavailable "
                                  f"during partition: {e}")
            if fed is not None:
                dcs = fed.get("dcs") or {}
                if set(dcs) != {"dc1", "dc2"}:
                    violations.append(
                        f"federation view dropped a DC during the "
                        f"partition (rows: {sorted(dcs)})")
                row2 = dcs.get("dc2") or {}
                rep_row = row2.get("replication") or {}
                if not rep_row.get("diverged"):
                    violations.append(
                        "federation view: dc2 row does not surface "
                        "its replication divergence")
                detail["federation_during_partition"] = {
                    "dcs": sorted(dcs),
                    "dc2_replication": rep_row}

            # ---------------- phase 3: heal + reconverge
            plan.append(["heal", "dc2->dc1"])
            injected.append([round(time.time() - t0, 2), "heal",
                             "dc2->dc1"])
            flight.emit("chaos.fault.healed",
                        labels={"fault": "wan_sever",
                                "target": "dc2->dc1"})
            wan.heal_link("dc2", "dc1")
            deadline = time.time() + CONVERGE_S
            reconverged = False
            while time.time() < deadline and not reconverged:
                reconverged = all(
                    not ck.check_divergence()["diverged"]
                    for ck in checkers)
                if not reconverged:
                    _nap(0.5)
            if not reconverged:
                violations.append(
                    f"heal: replication divergence did not converge "
                    f"to zero within {CONVERGE_S}s")
            stats = rep_statuses(dc2)
            still = [t for t, r in stats.items() if r.get("Diverged")]
            if reconverged and still:
                violations.append(
                    f"heal: dc2 still reports {still} diverged after "
                    f"the harness checker converged")
            # post-heal recovery SLO: the severed direction serves
            t_heal = time.time()
            post = cross_dc(c2, "dc1", "wan/healed",
                            timeout=RECOVERY_SLO_S)
            while not post["ok"] \
                    and time.time() - t_heal < RECOVERY_SLO_S:
                _nap(0.3)
                post = cross_dc(c2, "dc1", "wan/healed",
                                timeout=RECOVERY_SLO_S)
            if not post["ok"]:
                violations.append(
                    f"heal: dc2→dc1 writes never recovered within "
                    f"{RECOVERY_SLO_S}s ({post})")
            # the diverged→converged transitions journaled on dc2
            names = set()
            for i in dc2.alive_ids():
                try:
                    evs, _ = dc2.client(i, timeout=2.0).agent_events()
                    names |= {e.get("Name") for e in evs}
                except (ApiError, OSError):
                    continue
            for want in ("replication.diverged",
                         "replication.converged"):
                if want not in names:
                    violations.append(
                        f"flight: {want} never journaled on any dc2 "
                        f"node across the partition arc")
            detail.update({
                "diverged_types": diverged_types,
                "in_cluster_diverged": in_cluster,
                "max_lag_s": round(max_lag, 2),
                "asym_forward_ok": fwd["ok"],
                "cut_latency_s": round(cut["lat"], 2),
                "recovered": post["ok"],
                "statuses_after": {t: {k: r.get(k) for k in
                                       ("Diverged", "LagSeconds",
                                        "Rounds")}
                                   for t, r in stats.items()},
            })
        except Exception:
            import traceback
            tb = traceback.format_exc()
            violations.append(
                f"scenario crashed: {tb.strip().splitlines()[-1]}")
            detail["traceback"] = tb
        finally:
            if wan is not None:
                wan.stop()
            try:
                tmp.cleanup()
            except OSError:
                pass
    rows, _ = recorder.read_page(since=0)
    events = "\n".join(
        json.dumps({"ts": round(r["ts"], 3), "node": "nemesis",
                    "name": r["name"], "labels": r["labels"]},
                   sort_keys=True) for r in rows)
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()).hexdigest()[:16]
    return {"scenario": "live_wan_partition", "seed": seed,
            "ok": not violations, "violations": violations,
            "digest": digest, "plan": plan, "injected": injected,
            "detail": detail,
            "repro": f"python tools/chaos_live.py --scenario "
                     f"live_wan_partition --seed {seed}",
            "events": events}


def _xds_endpoint_map(rows: List[dict]) -> Dict[str, set]:
    """{service: {(addr, port), ...}} off a list of EDS
    ClusterLoadAssignment rows (cluster_name's first dot segment is
    the service; chain clusters are `<target>.internal.<td>` and plain
    upstreams are the bare destination name)."""
    out: Dict[str, set] = {}
    for row in rows:
        svc = str(row.get("cluster_name", "")).split(".")[0]
        eps = set()
        for grp in row.get("endpoints") or []:
            for lb in grp.get("lb_endpoints") or []:
                sa = ((lb.get("endpoint") or {}).get("address") or
                      {}).get("socket_address") or {}
                if sa:
                    eps.add((sa.get("address"), sa.get("port_value")))
        out[svc] = eps
    return out


def _xds_stage_budget_s() -> Tuple[float, dict]:
    """The tight phase-A stale-route SLO, derived from the committed
    XDSVIS_r01.json stage summaries (ISSUE 19: dereg→last-push lag is
    judged against the measured rebuild+push p99, not a magic
    number).  200× the per-change pipeline cost, floored at 2 s so a
    loaded CI box cannot flake the invariant."""
    rebuild_ms, push_ms, src = 2.2, 1.1, "fallback"
    try:
        # lint: ok=blocking-call (harness-side artifact read at setup)
        with open(os.path.join(REPO, "XDSVIS_r01.json")) as f:
            art = json.load(f)
        rows = art.get("rows") or []
        rebuild_ms = max(r["stages_ms"]["rebuild"]["p99_ms"]
                         for r in rows)
        push_ms = max(r["stages_ms"]["push"]["p99_ms"] for r in rows)
        src = "XDSVIS_r01.json"
    except (OSError, ValueError, KeyError):
        pass
    budget = max(2.0, 0.2 * (rebuild_ms + push_ms))
    return budget, {"rebuild_p99_ms": rebuild_ms,
                    "push_p99_ms": push_ms, "source": src}


def live_xds_churn_storm(seed: int, check: bool = False) -> dict:
    """Churn storm against the mesh control plane (ISSUE 19 tentpole
    c): proxies collapsed onto shared shapes park delta-mode xDS
    long-polls on a live multi-process cluster while a seeded storm of
    instance replacements, outright deregistrations, and intention
    flips churns the catalog.  Every config every watcher ever held is
    kept as a correlated timeline and judged by
    `check_stale_routes`: NO proxy may hold a config routing to
    a deregistered instance beyond the SLO — the hard gate at a
    failover-covering bound, and pre-kill deregs additionally at the
    tight budget derived from the committed XDSVIS_r01 stage
    summaries.  Mid-storm the node serving every watcher (the leader)
    is kill -9'd: watchers must fail over to a surviving server, the
    storm keeps writing through the new leader, and every proxy must
    reconverge to the correct final config.  `check=True` bounds the
    run for tier-1: a 2-server cluster, a short storm, no kill phase
    (quorum of two cannot lose a member) — the invariant checker and
    delta plane still run for real."""
    rng = random.Random(seed)
    plan: List[list] = []
    violations: List[str] = []
    detail: dict = {}
    injected: List[list] = []
    recorder = flight.FlightRecorder(clock=time.time,
                                     forward_to_log=False)
    t0 = time.time()

    def fault(kind, target):
        plan.append(["fault", kind])
        injected.append([round(time.time() - t0, 2), kind, target])
        flight.emit("chaos.fault.injected",
                    labels={"fault": kind, "target": target})

    n = 2 if check else 3
    shapes = 2
    routes = 2
    proxies = 4 if check else 8
    ops_a = 6 if check else 10
    ops_b = 0 if check else 6       # post-kill storm continues
    pace_s = 0.15 if check else 0.25
    STALE_SLO_S = 15.0              # hard gate, covers the failover
    RECONV_SLO_S = 20.0             # post-kill convergence deadline
    tight_slo_s, budget_src = _xds_stage_budget_s()

    deregs: List[dict] = []
    holds: Dict[str, List[tuple]] = {}
    hold_lock = threading.Lock()
    stats = {"delta": 0, "full": 0, "failovers": 0, "terminal": 0}
    stats_lock = threading.Lock()
    stop = threading.Event()
    threads: List[threading.Thread] = []
    cluster = None
    tmp = tempfile.TemporaryDirectory(prefix="chaos-xds-storm-")

    # catalog ground truth the storm maintains per route service
    port_cur = {r: 7000 + 500 * r for r in range(routes)}
    port_gen = {r: 0 for r in range(routes)}
    registered = {r: True for r in range(routes)}
    # the registrar node all catalog churn pins to (set post-election)
    reg = {"i": None}

    def put(cl_path, payload, timeout=20.0, pin=None):
        """Leader-forwarded write, retried through election windows;
        returns the apply-observed ts.  `pin` targets ONE node: agent
        service registrations are node-scoped, so all catalog churn
        goes through the surviving REGISTRAR node — the workload's
        own agent, which the nemesis never kills (it kills the node
        SERVING the watchers) — or replacement instances would land
        on a different node and orphan the dead node's entries."""
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            targets = [pin] if pin is not None else \
                cluster.alive_ids()
            for i in targets:
                try:
                    cluster.client(i, timeout=5.0)._call(
                        "PUT", cl_path,
                        body=json.dumps(payload).encode())
                    return time.time()
                except (ApiError, OSError) as e:
                    last = e
            _nap(0.2)
        raise RuntimeError(f"write {cl_path} never applied: {last}")

    def watcher(pid, start_idx):
        """One parked delta long-poll: maintains the proxy's HELD
        {service: endpoints} map from full snapshots + per-subset
        deltas, appending every received config to the correlated
        timeline; fails over (full refetch — version cursors are
        per-node) when its serving node dies."""
        si = start_idx
        cl = cluster.client(si, timeout=8.0)
        cur, primed = 0, False
        held: Dict[str, set] = {}

        def record():
            with hold_lock:
                holds[pid].append(
                    (time.time(),
                     {s: set(v) for s, v in held.items()}))

        while not stop.is_set():
            try:
                q = (f"?version={cur}&wait=3s&delta=1"
                     if primed else "")
                out = cl._call("GET", f"/v1/agent/xds/{pid}{q}")[0]
            except (ApiError, OSError) as e:
                if stop.is_set():
                    return
                if getattr(e, "code", None) == 410:
                    held = {}
                    record()        # terminal: proxy deregistered
                    with stats_lock:
                        stats["terminal"] += 1
                    return
                alive = cluster.alive_ids()
                if not alive:
                    _nap(0.2)
                    continue
                prev = si
                si = next((a for a in alive if a != si), alive[0])
                if si != prev:
                    with stats_lock:
                        stats["failovers"] += 1
                cl = cluster.client(si, timeout=8.0)
                cur, primed = 0, False
                _nap(0.05)
                continue
            v = int(out.get("VersionInfo", cur) or 0)
            if not primed:
                held = _xds_endpoint_map(
                    (out.get("Resources") or {}).get("endpoints")
                    or [])
                cur, primed = v, True
                record()
            elif v > cur:
                cur = v
                d = out.get("Delta")
                if d is not None:
                    held.update(_xds_endpoint_map(
                        (d.get("Changed") or {}).get("endpoints")
                        or []))
                    for name in ((d.get("Removed") or {})
                                 .get("endpoints") or []):
                        held[str(name).split(".")[0]] = set()
                    mode = "delta"
                else:
                    held = _xds_endpoint_map(
                        (out.get("Resources") or {})
                        .get("endpoints") or [])
                    mode = "full"
                with stats_lock:
                    stats[mode] += 1
                record()

    def storm_op(i):
        """One seeded churn op; records catalog deregs (instance
        replacement deregisters the old port implicitly — ports are
        never reused, so `cleared` is monotone for the checker)."""
        k = rng.randrange(3)
        if k == 0:
            tgt = rng.randrange(shapes)
            plan.append(["flip", tgt])
            put("/v1/connect/intentions",
                {"SourceName": f"storm-src-{i}",
                 "DestinationName": f"storm{tgt}",
                 "Action": "deny" if i % 2 else "allow"})
            return
        r = rng.randrange(routes)
        if k == 1 or not registered[r]:
            plan.append(["replace", r])
            old = port_cur[r] if registered[r] else None
            port_gen[r] += 1
            fresh = 7000 + 500 * r + port_gen[r]
            ts = put("/v1/agent/service/register",
                     {"Name": f"route-{r}", "ID": f"route-{r}",
                      "Port": fresh}, pin=reg["i"])
            if old is not None:
                deregs.append({"ts": ts, "service": f"route-{r}",
                               "address": "127.0.0.1", "port": old})
            port_cur[r], registered[r] = fresh, True
        else:
            plan.append(["dereg", r])
            ts = put(f"/v1/agent/service/deregister/route-{r}",
                     {}, pin=reg["i"])
            deregs.append({"ts": ts, "service": f"route-{r}",
                           "address": "127.0.0.1",
                           "port": port_cur[r]})
            registered[r] = False

    kill_ts = None
    with flight.use(recorder):
        try:
            cluster = LiveCluster(n, data_root=tmp.name, grpc=False)
            cluster.start()
            li = cluster.leader()
            leader_http = cluster.servers[li].http
            # the registrar: a follower the kill phase never touches,
            # so every route instance lives on ONE surviving node
            reg["i"] = next(i for i in range(n) if i != li)
            for r in range(routes):
                put("/v1/agent/service/register",
                    {"Name": f"route-{r}", "ID": f"route-{r}",
                     "Port": port_cur[r]}, pin=reg["i"])
            pids = []
            for i in range(proxies):
                s = i % shapes
                pid = f"storm{s}-{i}-sidecar-proxy"
                put("/v1/agent/service/register",
                    {"Name": f"storm{s}-sidecar-proxy", "ID": pid,
                     "Kind": "connect-proxy", "Port": 22000 + i,
                     "Proxy": {
                         "DestinationServiceName": f"storm{s}",
                         "Upstreams": [
                             {"DestinationName":
                              f"route-{s % routes}",
                              "LocalBindPort": 9200 + s}]}})
                pids.append(pid)
                holds[pid] = []
            # every watcher parks on the LEADER: the mid-storm kill -9
            # hits the node serving ALL of them
            for pid in pids:
                t = threading.Thread(target=watcher, args=(pid, li),
                                     name=f"storm-{pid}", daemon=True)
                threads.append(t)
                t.start()
            deadline = time.time() + 10.0
            while time.time() < deadline:
                with hold_lock:
                    if all(holds[p] for p in pids):
                        break
                _nap(0.05)
            with hold_lock:
                unprimed = [p for p in pids if not holds[p]]
            if unprimed:
                violations.append(
                    f"{len(unprimed)} watchers never primed their "
                    f"first config off {leader_http}")

            # ---------------- phase A: steady storm
            for i in range(ops_a):
                storm_op(i)
                _nap(pace_s)

            # ---------------- phase B: kill -9 the serving node
            if ops_b:
                fault("kill9", f"server{li} (serves every watcher)")
                cluster.kill(li)
                kill_ts = time.time()
                nli = cluster.leader(timeout=25.0)
                plan.append(["reelect"])
                detail["new_leader"] = f"server{nli}"
                for i in range(ops_b):
                    storm_op(ops_a + i)
                    _nap(pace_s)

            # ---------------- reconvergence: every proxy's held map
            # must match the final catalog
            want = {r: ({("127.0.0.1", port_cur[r])}
                        if registered[r] else set())
                    for r in range(routes)}
            t_conv = time.time()
            laggards = dict.fromkeys(pids)
            deadline = t_conv + RECONV_SLO_S
            while laggards and time.time() < deadline:
                with hold_lock:
                    for pid in list(laggards):
                        if not holds[pid]:
                            continue
                        r = (int(pid[5]) % routes)
                        got = holds[pid][-1][1].get(f"route-{r}",
                                                    set())
                        if got == want[r]:
                            del laggards[pid]
                if laggards:
                    _nap(0.05)
            reconverge_s = round(time.time() - t_conv, 2)
            for pid in sorted(laggards):
                r = int(pid[5]) % routes
                with hold_lock:
                    got = (holds[pid][-1][1].get(f"route-{r}")
                           if holds[pid] else None)
                violations.append(
                    f"reconvergence: {pid} still holds "
                    f"{sorted(got) if got else got} for route-{r} "
                    f"(want {sorted(want[r])}) "
                    f"{RECONV_SLO_S}s after the storm"
                    + (" and failover" if kill_ts else ""))

            stop.set()
            for t in threads:
                t.join(timeout=5.0)
            end_ts = time.time()

            # ---------------- the no-stale-route invariant
            v_hard, lags = check_stale_routes(
                deregs, holds, STALE_SLO_S, end_ts)
            violations += v_hard
            pre_kill = [d for d in deregs
                        if kill_ts is None
                        or d["ts"] < kill_ts - 1.0]
            v_tight, _ = check_stale_routes(
                pre_kill, holds, tight_slo_s, end_ts)
            violations += [f"stage-budget ({budget_src['source']}, "
                           f"{tight_slo_s:.2f}s): {v}"
                           for v in v_tight]
            lag_vals = [r["lag_s"] for r in lags]
            detail.update({
                "proxies": proxies, "shapes": shapes,
                "routes": routes,
                "ops": ops_a + ops_b, "deregs": len(deregs),
                "judged_pairs": len(lags),
                "lag_s": {"max": round(max(lag_vals), 3)
                          if lag_vals else 0.0,
                          "n": len(lag_vals)},
                "hard_slo_s": STALE_SLO_S,
                "tight_slo_s": round(tight_slo_s, 2),
                "stage_budget": budget_src,
                "reconverge_s": reconverge_s,
                "client_mode": {"delta": stats["delta"],
                                "full": stats["full"]},
                "failovers": stats["failovers"],
                "killed": kill_ts is not None,
            })
            if not check and stats["delta"] == 0:
                violations.append(
                    "delta plane never exercised: every push the "
                    "storm delivered was a full snapshot")
        except Exception:
            import traceback
            tb = traceback.format_exc()
            violations.append(
                f"scenario crashed: {tb.strip().splitlines()[-1]}")
            detail["traceback"] = tb
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=3.0)
            if cluster is not None:
                cluster.stop()
            try:
                tmp.cleanup()
            except OSError:
                pass
    rows, _ = recorder.read_page(since=0)
    events = "\n".join(
        json.dumps({"ts": round(r["ts"], 3), "node": "nemesis",
                    "name": r["name"], "labels": r["labels"]},
                   sort_keys=True) for r in rows)
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()).hexdigest()[:16]
    return {"scenario": "live_xds_churn_storm", "seed": seed,
            "ok": not violations, "violations": violations,
            "digest": digest, "plan": plan, "injected": injected,
            "detail": detail,
            "repro": f"python tools/chaos_live.py --scenario "
                     f"live_xds_churn_storm --seed {seed}",
            "events": events}


LIVE_SCENARIOS = {
    "live_partition_heal": live_partition_heal,
    "live_kill_leader_loop": live_kill_leader_loop,
    "live_rolling_restart": live_rolling_restart,
    "live_torn_disk_restart": live_torn_disk_restart,
    "live_pause_resume": live_pause_resume,
    "live_gateway_loss": live_gateway_loss,
    "live_stale_reads_through_election":
        live_stale_reads_through_election,
    "live_overload_shed": live_overload_shed,
    "live_wan_partition": live_wan_partition,
    "live_xds_churn_storm": live_xds_churn_storm,
}

# the bounded tier-1 smoke (chaos_soak --check): kill -9 the leader,
# restart on the same data-dir, prove durability + linearizability +
# election safety over live HTTP — the acceptance bar of ISSUE 9
SMOKE_SCENARIO = "live_kill_leader_loop"


def run_live_scenario(name: str, seed: int,
                      check: bool = False) -> dict:
    """Run one scenario; a crash (wedged bring-up, watchdog-reaped
    fleet, harness bug) becomes a FAILING report row — the runners'
    JSON summary, seed reproducer, and timeline-tail printing must
    survive anything the scenario throws, or CI gets a raw traceback
    instead of a gate verdict."""
    try:
        return LIVE_SCENARIOS[name](seed, check=check)
    except Exception:
        import traceback
        tb = traceback.format_exc()
        return {
            "scenario": name, "seed": seed, "ok": False,
            "violations": [f"scenario crashed: "
                           f"{tb.strip().splitlines()[-1]}"],
            "digest": "crashed", "plan": [], "injected": [],
            "detail": {"traceback": tb},
            "repro": f"python tools/chaos_live.py --scenario {name} "
                     f"--seed {seed}",
            "events": "",
        }


def run_live_smoke(seed: int) -> dict:
    """The tier-1 entry: one bounded live scenario under the hard
    SMOKE_BUDGET_S wall clock (enforced inside by the watchdog, and
    reported here so the caller can gate on it too)."""
    t0 = time.time()
    row = run_live_scenario(SMOKE_SCENARIO, seed, check=True)
    row["wall_s"] = round(time.time() - t0, 2)
    row["budget_s"] = SMOKE_BUDGET_S
    if row["wall_s"] > SMOKE_BUDGET_S:
        row["ok"] = False
        row["violations"].append(
            f"live smoke overran its wall budget: {row['wall_s']}s > "
            f"{SMOKE_BUDGET_S}s")
    return row
