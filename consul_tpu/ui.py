"""Web UI: a single-page dashboard served at /ui.

The reference ships an Ember monorepo served by agent/uiserver; this
framework serves a dependency-free single-file UI over the same /v1
APIs: services with instance health, nodes, membership summary, the KV
browser, intentions, and raft/autopilot state for server-backed agents.
Live updates ride the blocking-query index the API already exposes.
"""

PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>consul-tpu</title>
<style>
  :root { --bg:#0d1117; --panel:#161b22; --line:#30363d; --fg:#e6edf3;
          --dim:#8b949e; --ok:#3fb950; --warn:#d29922; --crit:#f85149;
          --acc:#58a6ff; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 system-ui,sans-serif; }
  header { display:flex; gap:16px; align-items:baseline;
           padding:12px 20px; border-bottom:1px solid var(--line); }
  header h1 { font-size:16px; margin:0; }
  header .sub { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:4px; padding:8px 20px 0; }
  nav button { background:none; border:none; color:var(--dim);
               padding:6px 12px; cursor:pointer; font-size:13px;
               border-bottom:2px solid transparent; }
  nav button.on { color:var(--fg); border-color:var(--acc); }
  main { padding:16px 20px; }
  table { border-collapse:collapse; width:100%; }
  th { text-align:left; color:var(--dim); font-weight:500;
       font-size:12px; padding:6px 10px;
       border-bottom:1px solid var(--line); }
  td { padding:6px 10px; border-bottom:1px solid var(--line); }
  .pill { display:inline-block; padding:1px 8px; border-radius:10px;
          font-size:12px; }
  .ok { background:#12381f; color:var(--ok); }
  .warn { background:#3a2d10; color:var(--warn); }
  .crit { background:#42181a; color:var(--crit); }
  .dim { color:var(--dim); }
  code { background:var(--panel); padding:1px 5px; border-radius:4px; }
  .cards { display:flex; gap:12px; margin-bottom:16px; flex-wrap:wrap; }
  .card { background:var(--panel); border:1px solid var(--line);
          border-radius:8px; padding:10px 16px; min-width:110px; }
  .card .n { font-size:22px; }
  .card .l { color:var(--dim); font-size:12px; }
</style>
</head>
<body>
<header><h1>consul-tpu</h1>
  <span class="sub" id="meta"></span></header>
<nav id="nav"></nav>
<main id="main">loading…</main>
<script>
const tabs = ["services","nodes","members","kv","intentions","mesh",
              "operator"];
let tab = location.hash.slice(1) || "services";
const $ = (h) => { const d = document.createElement("div");
                   d.innerHTML = h; return d; };
const esc = (s) => String(s).replace(/[&<>"]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const get = (p) => fetch(p).then(r => r.ok ? r.json() : null);
function pill(st) {
  const cls = st === "passing" || st === "alive" ? "ok"
            : st === "warning" ? "warn" : "crit";
  return `<span class="pill ${cls}">${esc(st)}</span>`;
}
async function renderServices() {
  // ONE summary call (/v1/internal/ui/services) — the N+1 per-service
  // health fetches would hammer the agent on every 5s refresh
  const rows = await get("/v1/internal/ui/services") || [];
  return `<table><tr><th>Service</th><th>Kind</th><th>Tags</th>
    <th>Instances</th><th>Health</th></tr>` + rows.map(s => {
    const health = [
      s.ChecksPassing ? `${pill("passing")} ${s.ChecksPassing}` : "",
      s.ChecksWarning ? `${pill("warning")} ${s.ChecksWarning}` : "",
      s.ChecksCritical ? `${pill("critical")} ${s.ChecksCritical}` : "",
    ].filter(Boolean).join(" ");
    return `<tr><td>${esc(s.Name)}</td>
      <td>${esc(s.Kind) || '<span class="dim">—</span>'}</td>
      <td>${(s.Tags || []).map(esc).join(", ")
            || '<span class="dim">—</span>'}</td>
      <td>${s.InstanceCount}</td>
      <td>${health || '<span class="dim">no checks</span>'}</td>
      </tr>`;}).join("") + `</table>`;
}
async function renderNodes() {
  const nodes = await get("/v1/internal/ui/nodes") || [];
  return `<table><tr><th>Node</th><th>Address</th><th>Checks</th></tr>`
    + nodes.map(n => {
      const c = n.Checks || {};
      const health = [
        c.passing ? `${pill("passing")} ${c.passing}` : "",
        c.warning ? `${pill("warning")} ${c.warning}` : "",
        c.critical ? `${pill("critical")} ${c.critical}` : "",
      ].filter(Boolean).join(" ");
      return `<tr><td>${esc(n.Node)}</td>
      <td><code>${esc(n.Address)}</code></td>
      <td>${health || '<span class="dim">—</span>'}</td></tr>`;
    }).join("") + `</table>`;
}
async function renderMesh() {
  const svcs = await get("/v1/internal/ui/services") || [];
  const gws = svcs.filter(s =>
    (s.Kind || "").indexOf("gateway") >= 0);
  let html = "";
  if (gws.length) {
    // one PARALLEL round-trip for all gateways (no serial N+1)
    const bounds = await Promise.all(gws.map(gw =>
      get(`/v1/catalog/gateway-services/${gw.Name}`)));
    const rows = gws.map((gw, i) =>
      `<tr><td>${esc(gw.Name)}</td><td>${esc(gw.Kind)}</td>
        <td>${(bounds[i] || []).map(b => esc(b.Service)).join(", ")
              || '<span class="dim">—</span>'}</td></tr>`).join("");
    html += `<h3>Gateways</h3><table><tr><th>Gateway</th><th>Kind</th>
      <th>Bound services</th></tr>${rows}</table>`;
  } else {
    html += `<p class="dim">no gateways registered</p>`;
  }
  const roots = await get("/v1/connect/ca/roots");
  if (roots) {
    html += `<h3>CA roots</h3><table><tr><th>Root</th><th>Active</th>
      </tr>` + roots.Roots.map(r => `<tr><td><code>${esc(r.ID)}</code>
      </td><td>${r.Active ? "★" : ""}</td></tr>`).join("")
      + `</table>
      <p class="dim">trust domain <code>${esc(roots.TrustDomain)}
      </code></p>`;
  }
  return html;
}
async function renderMembers() {
  const m = await get("/v1/agent/metrics") || {Gauges: []};
  const g = Object.fromEntries(m.Gauges.map(x => [x.Name, x.Value]));
  const cards = ["alive","failed","left","total"].map(k =>
    `<div class="card"><div class="n">${g["consul.members."+k] ?? "—"}
     </div><div class="l">${k}</div></div>`).join("");
  const mem = await get("/v1/agent/members?limit=100") || [];
  const statusNames = {1: "alive", 3: "left", 4: "failed"};
  const anySeg = mem.some(x => x.Tags && x.Tags.segment);
  return `<div class="cards">${cards}</div>
    <table><tr><th>Member</th>${anySeg ? "<th>Segment</th>" : ""}
    <th>Status</th></tr>` +
    mem.map(x => `<tr><td>${esc(x.Name)}</td>
      ${anySeg ? `<td>${esc((x.Tags && x.Tags.segment) || "")
        || '<span class="dim">&lt;default&gt;</span>'}</td>` : ""}
      <td>${pill(statusNames[x.Status] || String(x.Status))}
      </td></tr>`).join("") + `</table>
    <p class="dim">first 100 of ${g["consul.members.total"] ?? "?"}</p>`;
}
async function renderKV() {
  // ONE recurse fetch — per-key GETs would race the 5s refresh
  const rows = await get("/v1/kv/?recurse") || [];
  return `<table><tr><th>Key</th><th>Value</th></tr>` +
    rows.slice(0, 200).map(v => {
      const val = v.Value ? atob(v.Value) : "";
      return `<tr><td><code>${esc(v.Key)}</code></td>
        <td>${esc(val.slice(0, 120))}</td></tr>`;
    }).join("") + `</table>`;
}
async function renderIntentions() {
  const its = await get("/v1/connect/intentions") || [];
  return `<table><tr><th>Source</th><th>Destination</th><th>Action</th>
    <th>Precedence</th></tr>` + its.map(i =>
    `<tr><td>${esc(i.SourceName)}</td><td>${esc(i.DestinationName)}</td>
     <td>${pill(i.Action === "allow" ? "passing" : "critical")}</td>
     <td>${i.Precedence}</td></tr>`).join("") + `</table>`;
}
async function renderOperator() {
  const cfg = await get("/v1/operator/raft/configuration");
  if (!cfg) return `<p class="dim">not a server-backed agent</p>`;
  const h = await get("/v1/operator/autopilot/health");
  return `<table><tr><th>Server</th><th>Leader</th><th>Healthy</th></tr>`
    + cfg.Servers.map(s => {
      const hs = h && h.Servers.find(x => x.ID === s.ID);
      return `<tr><td>${esc(s.Node)}</td>
        <td>${s.Leader ? "★" : ""}</td>
        <td>${hs ? pill(hs.Healthy ? "passing" : "critical") : "—"}
        </td></tr>`;}).join("") + `</table>`;
}
const renderers = {services: renderServices, nodes: renderNodes,
  members: renderMembers, kv: renderKV, intentions: renderIntentions,
  mesh: renderMesh, operator: renderOperator};
async function render() {
  document.getElementById("nav").innerHTML = tabs.map(t =>
    `<button class="${t === tab ? "on" : ""}"
      onclick="location.hash='${t}'">${t}</button>`).join("");
  const self = await get("/v1/agent/self");
  if (self) document.getElementById("meta").textContent =
    `${self.Config.NodeName} · ${self.Config.Datacenter} · ` +
    `v${self.Config.Version}`;
  document.getElementById("main").innerHTML =
    await renderers[tab]() || "";
}
window.addEventListener("hashchange", () => {
  tab = location.hash.slice(1) || "services"; render(); });
render();
setInterval(render, 5000);
</script>
</body>
</html>
"""
