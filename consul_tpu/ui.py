"""Web UI: a dependency-free single-page APPLICATION served at /ui.

The reference ships an Ember monorepo (ui/packages/consul-ui, ~1.3k
files) served by agent/uiserver; this framework serves one hand-written
HTML file over the same /v1 APIs with the same day-to-day capabilities
(VERDICT r3 missing #3 / next #5):

  read       services / nodes / members / mesh / operator views
  detail     per-service page (instances + checks + upstreams +
             compiled discovery chain) and per-node page (services +
             checks) — the reference's service/node detail routes
  mutate     KV editor (create/edit/delete), intention
             create/edit/delete, token & policy browsing with detail
  live       the active view long-polls its primary endpoint with the
             blocking-query index (?index=N&wait=25s) and re-renders
             on change — no fixed refresh tick needed
  acl        an X-Consul-Token box (persisted in localStorage) rides
             every request, like the reference UI's token setting

Not an Ember port by design: the tpu-native framework keeps its whole
browser surface auditable in one file.
"""

PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>consul-tpu</title>
<style>
  :root { --bg:#0d1117; --panel:#161b22; --line:#30363d; --fg:#e6edf3;
          --dim:#8b949e; --ok:#3fb950; --warn:#d29922; --crit:#f85149;
          --acc:#58a6ff; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 system-ui,sans-serif; }
  header { display:flex; gap:16px; align-items:baseline;
           padding:12px 20px; border-bottom:1px solid var(--line); }
  header h1 { font-size:16px; margin:0; }
  header .sub { color:var(--dim); font-size:12px; }
  header .tok { margin-left:auto; }
  nav { display:flex; gap:4px; padding:8px 20px 0; }
  nav button { background:none; border:none; color:var(--dim);
               padding:6px 12px; cursor:pointer; font-size:13px;
               border-bottom:2px solid transparent; }
  nav button.on { color:var(--fg); border-color:var(--acc); }
  main { padding:16px 20px; max-width:1100px; }
  table { border-collapse:collapse; width:100%; }
  th { text-align:left; color:var(--dim); font-weight:500;
       font-size:12px; padding:6px 10px;
       border-bottom:1px solid var(--line); }
  td { padding:6px 10px; border-bottom:1px solid var(--line); }
  .pill { display:inline-block; padding:1px 8px; border-radius:10px;
          font-size:12px; }
  .ok { background:#12381f; color:var(--ok); }
  .warn { background:#3a2d10; color:var(--warn); }
  .crit { background:#42181a; color:var(--crit); }
  .dim { color:var(--dim); }
  .ok { color:var(--ok); } .bad { color:var(--crit); }
  .topo { display:flex; gap:24px; align-items:flex-start; }
  .topo > div { flex:1; }
  .tpself { flex:0 0 auto; align-self:center; }
  .tpnode { border:1px solid var(--line); border-radius:8px;
            background:var(--panel); padding:8px 12px;
            margin:6px 0; }
  code { background:var(--panel); padding:1px 5px; border-radius:4px; }
  .cards { display:flex; gap:12px; margin-bottom:16px; flex-wrap:wrap; }
  .card { background:var(--panel); border:1px solid var(--line);
          border-radius:8px; padding:10px 16px; min-width:110px; }
  .card .n { font-size:22px; }
  .card .l { color:var(--dim); font-size:12px; }
  a { color:var(--acc); text-decoration:none; cursor:pointer; }
  input, textarea, select {
    background:var(--panel); color:var(--fg); font:13px monospace;
    border:1px solid var(--line); border-radius:6px; padding:6px 8px; }
  textarea { width:100%; min-height:140px; }
  button.act { background:var(--acc); color:#04121f; border:none;
               border-radius:6px; padding:6px 14px; cursor:pointer;
               font-size:13px; }
  button.del { background:var(--crit); color:#fff; border:none;
               border-radius:6px; padding:6px 14px; cursor:pointer;
               font-size:13px; }
  .row { display:flex; gap:8px; margin:8px 0; align-items:center;
         flex-wrap:wrap; }
  .msg { padding:8px 12px; border-radius:6px; margin:8px 0;
         background:#12381f; color:var(--ok); }
  .msg.err { background:#42181a; color:var(--crit); }
  h3 { margin:18px 0 8px; font-size:14px; }
  pre { background:var(--panel); border:1px solid var(--line);
        border-radius:8px; padding:10px; overflow:auto; }
</style>
</head>
<body>
<header><h1>consul-tpu</h1>
  <span class="sub" id="meta"></span>
  <span class="tok">token
    <input id="tok" size="28" placeholder="X-Consul-Token"></span>
</header>
<nav id="nav"></nav>
<main id="main">loading…</main>
<script>
const tabs = ["services","nodes","members","kv","intentions","acl",
              "mesh","operator","metrics"];
let gen = 0;                         // render generation (watch cancel)
const esc = (s) => String(s ?? "").replace(/[&<>"'\\\\]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
         "'":"&#39;","\\\\":"&#92;"}[c]));
const tokBox = document.getElementById("tok");
tokBox.value = localStorage.getItem("consul_token") || "";
tokBox.addEventListener("change", () => {
  localStorage.setItem("consul_token", tokBox.value); render(); });
function hdrs() {
  const h = {};
  if (tokBox.value) h["X-Consul-Token"] = tokBox.value;
  return h;
}
async function get(p) {
  const r = await fetch(p, {headers: hdrs()});
  return r.ok ? r.json() : null;
}
async function send(method, p, body) {
  const r = await fetch(p, {method, headers: hdrs(),
    body: body === undefined ? undefined :
      (typeof body === "string" ? body : JSON.stringify(body))});
  if (!r.ok) throw new Error(await r.text() || r.status);
  return r.headers.get("content-type")?.includes("json")
    ? r.json() : r.text();
}
function flash(ok, text) {
  const el = document.getElementById("flash");
  if (el) { el.className = "msg" + (ok ? "" : " err");
            el.textContent = text; el.style.display = "block"; }
}
function pill(st) {
  const cls = st === "passing" || st === "alive" || st === "allow"
    ? "ok" : st === "warning" ? "warn" : "crit";
  return `<span class="pill ${cls}">${esc(st)}</span>`;
}
function route() {
  const h = location.hash.slice(1) || "services";
  const parts = h.split("/");
  return {tab: parts[0], args: parts.slice(1).map(decodeURIComponent)};
}

/* ----------------------------- services ----------------------------- */
async function renderServices() {
  const rows = await get("/v1/internal/ui/services") || [];
  return {watch: "/v1/catalog/services",
    html: `<table><tr><th>Service</th><th>Kind</th><th>Tags</th>
    <th>Instances</th><th>Health</th></tr>` + rows.map(s => {
    const health = [
      s.ChecksPassing ? `${pill("passing")} ${s.ChecksPassing}` : "",
      s.ChecksWarning ? `${pill("warning")} ${s.ChecksWarning}` : "",
      s.ChecksCritical ? `${pill("critical")} ${s.ChecksCritical}` : "",
    ].filter(Boolean).join(" ");
    return `<tr><td><a href="#service/${encodeURIComponent(s.Name)}">
      ${esc(s.Name)}</a></td>
      <td>${esc(s.Kind) || '<span class="dim">—</span>'}</td>
      <td>${(s.Tags || []).map(esc).join(", ")
            || '<span class="dim">—</span>'}</td>
      <td>${s.InstanceCount}</td>
      <td>${health || '<span class="dim">no checks</span>'}</td>
      </tr>`;}).join("") + `</table>`};
}
async function renderServiceDetail(name) {
  const [rows, chain] = await Promise.all([
    get(`/v1/health/service/${encodeURIComponent(name)}`),
    get(`/v1/discovery-chain/${encodeURIComponent(name)}`)]);
  let html = `<p><a href="#services">← services</a></p>
    <h3>${esc(name)} — instances</h3>`;
  html += `<table><tr><th>Node</th><th>Address</th><th>Port</th>
    <th>Checks</th></tr>` + (rows || []).map(r => {
    const checks = (r.Checks || []).map(c =>
      `${pill(c.Status)} ${esc(c.Name)}`).join(" ");
    return `<tr><td><a href="#node/${encodeURIComponent(r.Node.Node)}">
      ${esc(r.Node.Node)}</a></td>
      <td><code>${esc(r.Service.Address || r.Node.Address)}</code></td>
      <td>${r.Service.Port}</td><td>${checks || "—"}</td></tr>`;
  }).join("") + `</table>`;
  // sidecars registered with this service as their destination expose
  // the upstream set — /v1/catalog/connect/<name> lists the proxies
  // FOR the service regardless of what the proxy itself is named
  const cat = await get(`/v1/catalog/connect/` +
                        encodeURIComponent(name));
  const ups = (cat || []).flatMap(r =>
    ((r.ServiceProxy || {}).Upstreams) || []);
  if (ups.length) {
    html += `<h3>upstreams</h3><table><tr><th>Destination</th>
      <th>Local bind</th></tr>` + ups.map(u =>
      `<tr><td><a href="#service/${encodeURIComponent(
         u.DestinationName)}">${esc(u.DestinationName)}</a></td>
       <td>${u.LocalBindPort || "—"}</td></tr>`).join("") + `</table>`;
  }
  if (chain && chain.Chain) {
    const ch = chain.Chain;
    const nodes = Object.entries(ch.Nodes || {}).map(([id, n]) =>
      `<tr><td><code>${esc(id)}</code></td><td>${esc(n.Type)}</td>
       <td>${n.Type === "splitter" ? (n.Splits || []).map(s =>
             `${s.Weight}% → <code>${esc(s.Node)}</code>`).join(", ")
           : n.Type === "router" ? `${(n.Routes || []).length} routes`
           : esc(n.Target || n.Resolver || "")}</td></tr>`).join("");
    html += `<h3>discovery chain
      <span class="dim">(protocol ${esc(ch.Protocol)})</span></h3>
      <table><tr><th>Node</th><th>Type</th><th>Detail</th></tr>
      ${nodes}</table>`;
  }
  // topology: upstream -> svc -> downstream columns with intention
  // allow/deny coloring (the reference UI's topology view backed by
  // /v1/internal/ui/service-topology, agent/ui_endpoint.go)
  const topo = await get(`/v1/internal/ui/service-topology/` +
                         encodeURIComponent(name));
  if (topo && ((topo.Upstreams || []).length ||
               (topo.Downstreams || []).length)) {
    const cell = (s, dir) => {
      const ok = (s.Intention || {}).Allowed;
      const health = s.ChecksCritical ? "critical"
        : s.ChecksWarning ? "warning" : "passing";
      return `<div class="tpnode">
        <a href="#service/${encodeURIComponent(s.Name)}">
          ${esc(s.Name)}</a> ${pill(health)}<br>
        <span class="dim">${s.InstanceCount} inst ·
          ${esc(s.Source || "")}</span><br>
        ${ok ? `<span class="ok">→ allowed</span>`
             : `<span class="bad">→ denied</span>`}
        ${(s.Intention || {}).HasExact ?
          `<span class="dim">(intention)</span>` :
          `<span class="dim">(default)</span>`}
      </div>`;
    };
    html += `<h3>topology
      <span class="dim">(protocol ${esc(topo.Protocol)}${
        topo.TransparentProxy ? " · transparent proxy" : ""})</span>
      </h3>
      <div class="topo">
       <div><h4>upstreams</h4>${(topo.Upstreams || [])
         .map(s => cell(s, "up")).join("") || `<span class="dim">
         none</span>`}</div>
       <div class="tpself"><h4>&nbsp;</h4><div class="tpnode">
         <b>${esc(name)}</b></div></div>
       <div><h4>downstreams</h4>${(topo.Downstreams || [])
         .map(s => cell(s, "down")).join("") || `<span class="dim">
         none</span>`}</div>
      </div>`;
  }
  return {watch: `/v1/health/service/${encodeURIComponent(name)}`,
          html};
}

/* ------------------------------ nodes ------------------------------- */
async function renderNodes() {
  const nodes = await get("/v1/internal/ui/nodes") || [];
  return {watch: "/v1/catalog/nodes",
    html: `<table><tr><th>Node</th><th>Address</th><th>Checks</th></tr>`
    + nodes.map(n => {
      const c = n.Checks || {};
      const health = [
        c.passing ? `${pill("passing")} ${c.passing}` : "",
        c.warning ? `${pill("warning")} ${c.warning}` : "",
        c.critical ? `${pill("critical")} ${c.critical}` : "",
      ].filter(Boolean).join(" ");
      return `<tr><td><a href="#node/${encodeURIComponent(n.Node)}">
      ${esc(n.Node)}</a></td>
      <td><code>${esc(n.Address)}</code></td>
      <td>${health || '<span class="dim">—</span>'}</td></tr>`;
    }).join("") + `</table>`};
}
async function renderNodeDetail(name) {
  const [cat, checks] = await Promise.all([
    get(`/v1/catalog/node/${encodeURIComponent(name)}`),
    get(`/v1/health/node/${encodeURIComponent(name)}`)]);
  let html = `<p><a href="#nodes">← nodes</a></p>`;
  if (!cat || !cat.Node) return {html: html + `<p class="dim">unknown
    node ${esc(name)}</p>`};
  html += `<h3>${esc(name)}
    <span class="dim"><code>${esc(cat.Node.Address)}</code></span></h3>`;
  const svcs = Object.values(cat.Services || {});
  html += `<h3>services</h3><table><tr><th>Service</th><th>ID</th>
    <th>Port</th><th>Kind</th></tr>` + svcs.map(s =>
    `<tr><td><a href="#service/${encodeURIComponent(s.Service)}">
      ${esc(s.Service)}</a></td><td><code>${esc(s.ID)}</code></td>
     <td>${s.Port}</td><td>${esc(s.Kind || "")}</td></tr>`).join("")
    + `</table>`;
  html += `<h3>checks</h3><table><tr><th>Check</th><th>Status</th>
    <th>Output</th></tr>` + (checks || []).map(c =>
    `<tr><td>${esc(c.Name)}</td><td>${pill(c.Status)}</td>
     <td class="dim">${esc((c.Output || "").slice(0, 80))}</td></tr>`
    ).join("") + `</table>`;
  return {watch: `/v1/health/node/${encodeURIComponent(name)}`, html};
}

/* ------------------------------- kv --------------------------------- */
async function renderKV(prefix) {
  prefix = prefix || "";
  const keys = await get(`/v1/kv/${encodeURIComponent(prefix)
    .replace(/%2F/g, "/")}?keys`) || [];
  let html = `<div id="flash" style="display:none"></div>
    <div class="row">
      <input id="newkey" placeholder="new key" size="40"
             value="${esc(prefix)}">
      <button class="act" onclick="kvOpen()">create / open</button>
    </div>`;
  if (prefix) html += `<p><a href="#kv">← all keys</a>
    <code>${esc(prefix)}</code></p>`;
  html += `<table><tr><th>Key</th><th></th></tr>` +
    keys.slice(0, 500).map(k =>
      `<tr><td><code>${esc(k)}</code></td>
       <td><a href="#kv/edit/${encodeURIComponent(k)}">edit</a></td>
       </tr>`).join("") + `</table>`;
  // watch the KEY LIST, not ?recurse — the watch only needs an index
  // to ride, and recurse would re-download every value per wake
  return {watch: `/v1/kv/?keys`, html};
}
function kvOpen() {
  const k = document.getElementById("newkey").value.trim();
  if (k) location.hash = `kv/edit/${encodeURIComponent(k)}`;
}
function kvRouteKey() {
  // the key ALWAYS comes from the route, never from an inline JS
  // string — a quote in a key name must not become script
  return route().args.slice(1).join("/");
}
async function renderKVEdit(key) {
  const rows = await get(`/v1/kv/${encodeURIComponent(key)
    .replace(/%2F/g, "/")}`);
  let val = "", binary = false;
  if (rows && rows[0] && rows[0].Value) {
    // atob gives Latin-1 code units; decode the BYTES as UTF-8 so
    // non-ASCII text round-trips (fetch re-encodes the textarea as
    // UTF-8 on save).  Truly binary values are not textarea-editable:
    // flag them read-only instead of corrupting on save.
    const bytes = Uint8Array.from(atob(rows[0].Value),
                                  c => c.charCodeAt(0));
    try { val = new TextDecoder("utf-8", {fatal: true}).decode(bytes); }
    catch (e) { binary = true;
      val = [...bytes].map(b =>
        b.toString(16).padStart(2, "0")).join(" "); }
  }
  const meta = rows && rows[0] ? `modify index ${rows[0].ModifyIndex}
    · flags ${rows[0].Flags}` : "new key";
  return {noRefresh: true, html: `<p><a href="#kv">← keys</a></p>
    <h3><code>${esc(key)}</code> <span class="dim">${meta}${binary
      ? " · binary (read-only hex)" : ""}</span></h3>
    <div id="flash" style="display:none"></div>
    <textarea id="kvval" ${binary ? "readonly" : ""}>${esc(val)}</textarea>
    <div class="row">
      ${binary ? "" :
        `<button class="act" onclick="kvSave()">save</button>`}
      <button class="del" onclick="kvDelete()">delete</button>
    </div>`};
}
async function kvSave() {
  try {
    await send("PUT", `/v1/kv/${encodeURIComponent(kvRouteKey())
      .replace(/%2F/g, "/")}`,
      document.getElementById("kvval").value);
    flash(true, "saved");
  } catch (e) { flash(false, "save failed: " + e.message); }
}
async function kvDelete() {
  try {
    await send("DELETE", `/v1/kv/${encodeURIComponent(kvRouteKey())
      .replace(/%2F/g, "/")}`);
    location.hash = "kv";
  } catch (e) { flash(false, "delete failed: " + e.message); }
}

/* ---------------------------- intentions ---------------------------- */
async function renderIntentions() {
  const its = await get("/v1/connect/intentions") || [];
  return {watch: "/v1/connect/intentions",
    html: `<div id="flash" style="display:none"></div>
    <div class="row">
      <input id="isrc" placeholder="source" size="16">
      <input id="idst" placeholder="destination" size="16">
      <select id="iact"><option>allow</option><option>deny</option>
      </select>
      <button class="act" onclick="intentionCreate()">create</button>
    </div>
    <table><tr><th>Source</th><th>Destination</th><th>Action</th>
    <th>Precedence</th><th></th></tr>` + its.map(i =>
    `<tr><td>${esc(i.SourceName)}</td><td>${esc(i.DestinationName)}</td>
     <td>${pill(i.Action)}</td>
     <td>${i.Precedence}</td>
     <td><a data-iop="flip" data-id="${esc(i.ID)}"
            data-action="${i.Action === "allow" ? "deny" : "allow"}">
          flip</a> ·
         <a data-iop="delete" data-id="${esc(i.ID)}">delete</a>
     </td></tr>`).join("") + `</table>`};
}
async function intentionCreate() {
  try {
    await send("PUT", "/v1/connect/intentions", {
      SourceName: document.getElementById("isrc").value.trim(),
      DestinationName: document.getElementById("idst").value.trim(),
      Action: document.getElementById("iact").value});
    render();
  } catch (e) { flash(false, "create failed: " + e.message); }
}
async function intentionFlip(id, action) {
  try { await send("PUT", `/v1/connect/intentions/${id}`,
                   {Action: action}); render(); }
  catch (e) { flash(false, "update failed: " + e.message); }
}
async function intentionDelete(id) {
  try { await send("DELETE", `/v1/connect/intentions/${id}`); render(); }
  catch (e) { flash(false, "delete failed: " + e.message); }
}

/* ------------------------------- acl -------------------------------- */
async function renderACL() {
  const [toks, pols] = await Promise.all([
    get("/v1/acl/tokens"), get("/v1/acl/policies")]);
  let html = `<div id="flash" style="display:none"></div>`;
  if (toks === null && pols === null) {
    return {html: html + `<p class="dim">ACL endpoints denied — set a
      token with acl:read (or ACLs are disabled; then there is nothing
      to manage).</p>`};
  }
  html += `<h3>tokens</h3><table><tr><th>Accessor</th>
    <th>Description</th><th>Policies</th><th>Identities</th></tr>` +
    (toks || []).map(t => `<tr>
      <td><a href="#acl/token/${esc(t.AccessorID)}">
        <code>${esc(t.AccessorID.slice(0, 8))}…</code></a></td>
      <td>${esc(t.Description)}</td>
      <td>${(t.Policies || []).map(p => esc(p.Name)).join(", ")}</td>
      <td>${[...(t.ServiceIdentities || []).map(s =>
              "svc:" + esc(s.ServiceName)),
             ...(t.NodeIdentities || []).map(n =>
              "node:" + esc(n.NodeName))].join(", ")
            || '<span class="dim">—</span>'}</td></tr>`).join("")
    + `</table>`;
  html += `<h3>policies</h3><table><tr><th>Name</th><th>ID</th>
    <th>Description</th></tr>` + (pols || []).map(p => `<tr>
      <td><a href="#acl/policy/${esc(p.ID)}">${esc(p.Name)}</a></td>
      <td><code>${esc(p.ID.slice(0, 8))}…</code></td>
      <td>${esc(p.Description)}</td></tr>`).join("") + `</table>`;
  return {html};
}
async function renderTokenDetail(id) {
  const t = await get(`/v1/acl/token/${encodeURIComponent(id)}`);
  if (!t) return {html: `<p><a href="#acl">← acl</a></p>
    <p class="dim">token not readable</p>`};
  return {html: `<p><a href="#acl">← acl</a></p>
    <h3>token <code>${esc(t.AccessorID)}</code></h3>
    <pre>${esc(JSON.stringify(t, null, 2))}</pre>`};
}
async function renderPolicyDetail(id) {
  const p = await get(`/v1/acl/policy/${encodeURIComponent(id)}`);
  if (!p) return {html: `<p><a href="#acl">← acl</a></p>
    <p class="dim">policy not readable</p>`};
  return {html: `<p><a href="#acl">← acl</a></p>
    <h3>policy ${esc(p.Name)}</h3>
    <pre>${esc(p.Rules || "")}</pre>
    <pre>${esc(JSON.stringify({ID: p.ID,
      Description: p.Description}, null, 2))}</pre>`};
}

/* ------------------------- members/mesh/operator --------------------- */
async function renderMembers() {
  const m = await get("/v1/agent/metrics") || {Gauges: []};
  const g = Object.fromEntries(m.Gauges.map(x => [x.Name, x.Value]));
  const cards = ["alive","failed","left","total"].map(k =>
    `<div class="card"><div class="n">${g["consul.members."+k] ?? "—"}
     </div><div class="l">${k}</div></div>`).join("");
  const mem = await get("/v1/agent/members?limit=100") || [];
  const statusNames = {1: "alive", 3: "left", 4: "failed"};
  const anySeg = mem.some(x => x.Tags && x.Tags.segment);
  return {html: `<div class="cards">${cards}</div>
    <table><tr><th>Member</th>${anySeg ? "<th>Segment</th>" : ""}
    <th>Status</th></tr>` +
    mem.map(x => `<tr><td>${esc(x.Name)}</td>
      ${anySeg ? `<td>${esc((x.Tags && x.Tags.segment) || "")
        || '<span class="dim">&lt;default&gt;</span>'}</td>` : ""}
      <td>${pill(statusNames[x.Status] || String(x.Status))}
      </td></tr>`).join("") + `</table>
    <p class="dim">first 100 of ${g["consul.members.total"] ?? "?"}
    </p>`};
}
async function renderMesh() {
  const svcs = await get("/v1/internal/ui/services") || [];
  const gws = svcs.filter(s => (s.Kind || "").indexOf("gateway") >= 0);
  let html = "";
  if (gws.length) {
    const bounds = await Promise.all(gws.map(gw =>
      get(`/v1/catalog/gateway-services/${gw.Name}`)));
    const rows = gws.map((gw, i) =>
      `<tr><td>${esc(gw.Name)}</td><td>${esc(gw.Kind)}</td>
        <td>${(bounds[i] || []).map(b => esc(b.Service)).join(", ")
              || '<span class="dim">—</span>'}</td></tr>`).join("");
    html += `<h3>Gateways</h3><table><tr><th>Gateway</th><th>Kind</th>
      <th>Bound services</th></tr>${rows}</table>`;
  } else {
    html += `<p class="dim">no gateways registered</p>`;
  }
  const roots = await get("/v1/connect/ca/roots");
  if (roots) {
    html += `<h3>CA roots</h3><table><tr><th>Root</th><th>Active</th>
      </tr>` + roots.Roots.map(r => `<tr><td><code>${esc(r.ID)}</code>
      </td><td>${r.Active ? "★" : ""}</td></tr>`).join("")
      + `</table>
      <p class="dim">trust domain <code>${esc(roots.TrustDomain)}
      </code></p>`;
  }
  return {html};
}
async function renderOperator() {
  const cfg = await get("/v1/operator/raft/configuration");
  if (!cfg) return {html:
    `<p class="dim">not a server-backed agent</p>`};
  const h = await get("/v1/operator/autopilot/health");
  return {html: `<table><tr><th>Server</th><th>Leader</th>
    <th>Healthy</th></tr>`
    + cfg.Servers.map(s => {
      const hs = h && h.Servers.find(x => x.ID === s.ID);
      return `<tr><td>${esc(s.Node)}</td>
        <td>${s.Leader ? "★" : ""}</td>
        <td>${hs ? pill(hs.Healthy ? "passing" : "critical") : "—"}
        </td></tr>`;}).join("") + `</table>`};
}

/* ----------------------------- metrics ------------------------------ */
// counter history across refreshes: name -> [{t, count}] ring (the
// reference's metrics-proxy role scoped to THIS agent's
// /v1/agent/metrics — http_register.go:98)
const mHist = {};
function mRecord(counters) {
  const t = Date.now() / 1000;
  for (const c of counters) {
    const h = mHist[c.Name] = mHist[c.Name] || [];
    h.push({t, count: c.Count});
    if (h.length > 60) h.shift();
  }
}
function mRate(name) {
  const h = mHist[name] || [];
  if (h.length < 2) return null;
  const a = h[h.length - 2], b = h[h.length - 1];
  // clamp at 0: a counter reset (agent restart) is not a negative rate
  return b.t > a.t ? Math.max(0, (b.count - a.count) / (b.t - a.t))
                   : null;
}
function spark(name) {
  const h = mHist[name] || [];
  if (h.length < 3) return "";
  const rates = [];
  for (let i = 1; i < h.length; i++)
    rates.push(h[i].t > h[i-1].t ?
      Math.max(0, (h[i].count - h[i-1].count) /
                  (h[i].t - h[i-1].t)) : 0);
  const mx = Math.max(...rates, 1e-9);
  const pts = rates.map((r, i) =>
    `${(i / (rates.length - 1)) * 96 + 2},` +
    `${18 - (r / mx) * 16}`).join(" ");
  return `<svg width="100" height="20" class="spark">
    <polyline points="${pts}" fill="none"
      stroke="var(--acc)" stroke-width="1.5"/></svg>`;
}
async function renderMetrics() {
  const m = await get("/v1/agent/metrics");
  if (!m) return {html: `<p class="dim">metrics unavailable</p>`};
  mRecord(m.Counters || []);
  const fmt = (v) => v == null ? `<span class="dim">—</span>`
    : v >= 100 ? v.toFixed(0) : v.toFixed(2);
  let html = `<p class="dim">sampled ${esc(m.Timestamp)} ·
    refreshes every 7s ·
    <a href="/v1/agent/metrics?format=prometheus">prometheus text</a>
    </p>`;
  html += `<h3>counters</h3>
    <table><tr><th>Name</th><th>Count</th><th>Rate/s</th>
    <th>Trend</th></tr>` + (m.Counters || []).map(c =>
    `<tr><td><code>${esc(c.Name)}</code></td><td>${c.Count}</td>
     <td>${fmt(mRate(c.Name))}</td>
     <td>${spark(c.Name)}</td></tr>`).join("") + `</table>`;
  if ((m.Gauges || []).length)
    html += `<h3>gauges</h3><table><tr><th>Name</th><th>Value</th>
      </tr>` + m.Gauges.map(g =>
      `<tr><td><code>${esc(g.Name)}</code></td><td>${g.Value}</td>
       </tr>`).join("") + `</table>`;
  if ((m.Samples || []).length)
    html += `<h3>samples <span class="dim">(ms)</span></h3>
      <table><tr><th>Name</th><th>Count</th><th>Mean</th><th>Min</th>
      <th>Max</th></tr>` + m.Samples.map(s =>
      `<tr><td><code>${esc(s.Name)}</code></td><td>${s.Count}</td>
       <td>${s.Mean}</td><td>${s.Min}</td><td>${s.Max}</td>
       </tr>`).join("") + `</table>`;
  return {html};
}

/* ------------------------------ router ------------------------------ */
const views = {
  services: () => renderServices(),
  service: (a) => renderServiceDetail(a[0]),
  nodes: () => renderNodes(),
  node: (a) => renderNodeDetail(a[0]),
  members: () => renderMembers(),
  kv: (a) => a[0] === "edit" ? renderKVEdit(a.slice(1).join("/"))
                             : renderKV(a.join("/")),
  intentions: () => renderIntentions(),
  acl: (a) => a[0] === "token" ? renderTokenDetail(a[1])
            : a[0] === "policy" ? renderPolicyDetail(a[1])
            : renderACL(),
  mesh: () => renderMesh(),
  operator: () => renderOperator(),
  metrics: () => renderMetrics(),
};
async function liveWatch(url, myGen) {
  // blocking-query loop: ride X-Consul-Index so the view re-renders
  // the moment its data changes (agent blocking queries; rpc.go:806)
  try {
    let r = await fetch(url, {headers: hdrs()});
    let idx = r.headers.get("X-Consul-Index");
    if (!idx) return;
    const sep = url.includes("?") ? "&" : "?";
    r = await fetch(`${url}${sep}index=${idx}&wait=25s`,
                    {headers: hdrs()});
    const idx2 = r.headers.get("X-Consul-Index");
    if (gen !== myGen) return;       // user navigated away
    if (idx2 && idx2 !== idx) { render(); return; }
    liveWatch(url, myGen);           // timeout: re-arm
  } catch (e) {
    // agent restarting / network blip: back off and re-render (which
    // re-arms the watch) rather than leaving the view stale forever
    setTimeout(() => { if (gen === myGen) render(); }, 5000);
  }
}
async function render() {
  const {tab, args} = route();
  const myGen = ++gen;
  document.getElementById("nav").innerHTML = tabs.map(t =>
    `<button class="${t === tab ? "on" : ""}"
      onclick="location.hash='${t}'">${t}</button>`).join("");
  const self = await get("/v1/agent/self");
  if (self) document.getElementById("meta").textContent =
    `${self.Config.NodeName} · ${self.Config.Datacenter} · ` +
    `v${self.Config.Version}`;
  const view = views[tab] || views.services;
  let out;
  try { out = await view(args); }
  catch (e) { out = {html: `<p class="dim">error: ${esc(e.message)}
    </p>`}; }
  if (gen !== myGen) return;
  document.getElementById("main").innerHTML = out.html || "";
  if (out.watch) liveWatch(out.watch, myGen);
  else if (!out.noRefresh)
    // views with no blocking-query primary (members/mesh/operator/acl)
    // keep the old dashboard's periodic refresh; editors (noRefresh)
    // must never wipe in-progress input
    setTimeout(() => { if (gen === myGen) render(); }, 7000);
}
// delegated handler for row actions: IDs travel as data-* attributes
// (read back via dataset, so no server value is ever parsed as JS)
document.getElementById("main").addEventListener("click", (ev) => {
  const a = ev.target.closest("a[data-iop]");
  if (!a) return;
  if (a.dataset.iop === "flip")
    intentionFlip(a.dataset.id, a.dataset.action);
  else if (a.dataset.iop === "delete") intentionDelete(a.dataset.id);
});
window.addEventListener("hashchange", render);
render();
</script>
</body>
</html>
"""
