"""Always-on tick profiler: per-pass EMA timings + recompile watchdog.

PROFILE_r06.json is a one-shot offline table (tools/profile_swim.py);
what operators need live is the same per-pass story cheap enough to
leave ON: an exponential moving average of each named pass's wall
time, sampled at the host-sync checkpoints the runtime already pays
(the oracle's advance/scrape boundaries, the bench's scan readbacks) —
never inside the jitted tick.

The second job is the recompile watchdog.  PR 2's discipline says the
hot scan compiles ONCE per topology; a silent recompile mid-run means
something perturbed a static config and the operator is paying
multi-second XLA compiles in production.  `note_cache_size()` tracks
each jitted entry point's trace-cache size between checkpoints: growth
past the first compile increments `consul.runtime.compiles` and
journals a `runtime.recompile` warning into the flight recorder
(consul_tpu/flight.py) so the event timeline shows WHEN the recompile
hit relative to elections/flaps.

Surfaced at /v1/agent/profile, stamped into bench.py /
tools/scale_sweep.py artifacts (ROADMAP item 3's re-baselining input),
and carried in debug bundles as profile.json.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

EMA_ALPHA = 0.2


class TickProfiler:
    def __init__(self, alpha: float = EMA_ALPHA):
        self.alpha = alpha
        self._lock = threading.Lock()
        # name -> [ema_s, last_s, count, total_s]
        self._passes: Dict[str, list] = {}
        # fn name -> last observed jit trace-cache size
        self._cache_sizes: Dict[str, int] = {}
        self.recompiles = 0

    # ---------------------------------------------------------------- passes

    def observe(self, name: str, dur_s: float) -> None:
        """Fold one pass duration into the EMA (one dict write under a
        lock — cheap enough for every host-sync checkpoint)."""
        with self._lock:
            row = self._passes.get(name)
            if row is None:
                self._passes[name] = [dur_s, dur_s, 1, dur_s]
            else:
                row[0] += self.alpha * (dur_s - row[0])
                row[1] = dur_s
                row[2] += 1
                row[3] += dur_s

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # ------------------------------------------------------------- recompiles

    def note_jit(self, fn_name: str, jitted_fn) -> None:
        """Probe a jitted entry point's trace-cache size and feed the
        watchdog — the one place that knows how to ask (older jax
        without `_cache_size` degrades to no signal)."""
        self.note_cache_size(
            fn_name, int(jitted_fn._cache_size())
            if hasattr(jitted_fn, "_cache_size") else None)

    def note_cache_size(self, fn_name: str, size: Optional[int]) -> None:
        """Record a jitted entry point's trace-cache size at a
        checkpoint.  The first compile is expected; any growth AFTER a
        compile exists is an unexpected recompile: count it and journal
        a warning event (the operator's 'why did this tick take 8 s'
        answer)."""
        if size is None:        # jax without _cache_size(): no signal
            return
        with self._lock:
            prev = self._cache_sizes.get(fn_name)
            self._cache_sizes[fn_name] = size
            unexpected = (prev is not None and prev >= 1
                          and size > prev)
        if unexpected:
            from consul_tpu import flight, telemetry
            with self._lock:
                self.recompiles += size - prev
            telemetry.incr_counter(("runtime", "compiles"),
                                   float(size - prev))
            try:
                flight.emit("runtime.recompile",
                            labels={"fn": fn_name,
                                    "cache_size": size})
            except ValueError:
                pass    # catalog drift must not break the hot path

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The /v1/agent/profile shape: per-pass EMA table + compile
        accounting, JSON-safe."""
        with self._lock:
            passes = {
                name: {"ema_ms": round(row[0] * 1000.0, 3),
                       "last_ms": round(row[1] * 1000.0, 3),
                       "count": row[2],
                       "total_ms": round(row[3] * 1000.0, 3)}
                for name, row in sorted(self._passes.items())}
            return {"passes": passes,
                    "alpha": self.alpha,
                    "compile_cache": dict(sorted(
                        self._cache_sizes.items())),
                    "recompiles": self.recompiles}

    def reset(self) -> None:
        with self._lock:
            self._passes.clear()
            self._cache_sizes.clear()
            self.recompiles = 0


_default = TickProfiler()


def default_profiler() -> TickProfiler:
    return _default
