"""Config entries + discovery-chain compilation (L7 routing).

The reference's centralized config entries (service-router /
service-splitter / service-resolver, agent/structs/config_entry.go)
compile per service into a discovery chain
(agent/consul/discoverychain/compile.go:57 Compile): a start node,
router nodes with path/header matches, splitter nodes with weighted
legs, and resolver nodes producing targets (optionally redirected or
with failover).  The chain is what the xDS layer turns into routes/
clusters; /v1/discovery-chain/<service> serves the compiled form.

Compilation here follows the same node graph: router → splitter →
resolver → target, with defaults synthesized for services that have no
entries (the implicit chain).
"""

from __future__ import annotations

from typing import Dict, List, Optional

# kinds accepted by the config-entry store; the L7 trio compiles into
# chains, the rest are stored/served for mesh-wide defaults
# (structs/config_entry.go kinds)
KINDS = ("service-router", "service-splitter", "service-resolver",
         "service-defaults", "proxy-defaults", "mesh",
         "ingress-gateway", "terminating-gateway")


def _entry(store, kind: str, name: str) -> Optional[dict]:
    return store.config_entry_get(kind, name)


def _resolver_node(store, service: str, chain: dict,
                   depth: int = 0) -> str:
    """Build (and register in chain) the resolver node for `service`,
    following redirects (compile.go resolver handling).  Returns the
    node id."""
    nid = f"resolver:{service}"
    if nid in chain["Nodes"]:
        return nid
    if depth > 8:
        # too-deep redirect chain: terminate with a plain resolver for
        # this service rather than a dangling node reference (the
        # reference errors; a black-holed pointer is the worst option)
        target = f"{service}.default.{chain['Datacenter']}"
        chain["Nodes"][nid] = {"Type": "resolver", "Name": service,
                               "Target": target, "Failover": [],
                               "RedirectDepthExceeded": True}
        chain["Targets"][target] = {"Service": service,
                                    "Datacenter": chain["Datacenter"]}
        return nid
    res = _entry(store, "service-resolver", service) or {}
    redirect = (res.get("redirect") or {}).get("service")
    if redirect and redirect != service:
        target = _resolver_node(store, redirect, chain, depth + 1)
        chain["Nodes"][nid] = {"Type": "resolver", "Name": service,
                               "Redirect": redirect, "Resolver": target}
        return nid
    target = f"{service}.default.{chain['Datacenter']}"
    failover = [
        {"Service": f.get("service", service),
         "Datacenters": f.get("datacenters") or []}
        for f in (res.get("failover") or {}).values()
    ] if isinstance(res.get("failover"), dict) else []
    chain["Nodes"][nid] = {
        "Type": "resolver", "Name": service,
        "ConnectTimeout": res.get("connect_timeout", "5s"),
        "Target": target,
        "Failover": failover,
    }
    chain["Targets"][target] = {"Service": service,
                                "Datacenter": chain["Datacenter"]}
    return nid


def _splitter_node(store, service: str, chain: dict) -> str:
    split = _entry(store, "service-splitter", service)
    if split is None:
        return _resolver_node(store, service, chain)
    nid = f"splitter:{service}"
    if nid in chain["Nodes"]:
        return nid
    legs = []
    for leg in split.get("splits") or []:
        svc = leg.get("service", service)
        legs.append({"Weight": leg.get("weight", 0),
                     "Node": _resolver_node(store, svc, chain)})
    chain["Nodes"][nid] = {"Type": "splitter", "Name": service,
                           "Splits": legs}
    return nid


def compile_chain(store, service: str, dc: str = "dc1") -> dict:
    """Compile `service`'s discovery chain (compile.go:57).

    Output shape mirrors structs.CompiledDiscoveryChain: ServiceName,
    StartNode, Nodes (router/splitter/resolver), Targets."""
    chain: Dict = {"ServiceName": service, "Datacenter": dc,
                   "Protocol": "tcp", "Nodes": {}, "Targets": {}}
    router = _entry(store, "service-router", service)
    if router is not None:
        nid = f"router:{service}"
        routes = []
        for r in router.get("routes") or []:
            match = r.get("match") or {}
            dest = (r.get("destination") or {}).get("service", service)
            headers = [{"Name": h.get("name", ""),
                        "Exact": h.get("exact", ""),
                        "Prefix": h.get("prefix", ""),
                        "Present": bool(h.get("present", False)),
                        "Regex": h.get("regex", "")}
                       for h in match.get("header") or []]
            routes.append({
                "Match": {"PathPrefix": match.get("path_prefix", ""),
                          "PathExact": match.get("path_exact", ""),
                          "Header": headers},
                "Node": _splitter_node(store, dest, chain),
            })
        # default catch-all to the service itself (compile.go appends
        # the implicit default route)
        routes.append({"Match": {"PathPrefix": "/"},
                       "Node": _splitter_node(store, service, chain)})
        chain["Nodes"][nid] = {"Type": "router", "Name": service,
                               "Routes": routes}
        chain["StartNode"] = nid
        chain["Protocol"] = "http"
    else:
        chain["StartNode"] = _splitter_node(store, service, chain)
        if f"splitter:{service}" in chain["Nodes"]:
            chain["Protocol"] = "http"
    return chain
