"""Config entries + discovery-chain compilation (L7 routing).

The reference's centralized config entries (service-router /
service-splitter / service-resolver, agent/structs/config_entry.go)
compile per service into a discovery chain
(agent/consul/discoverychain/compile.go:57 Compile): a start node,
router nodes with path/header matches, splitter nodes with weighted
legs, and resolver nodes producing targets (optionally redirected or
with failover).  The chain is what the xDS layer turns into routes/
clusters; /v1/discovery-chain/<service> serves the compiled form.

Compilation here follows the same node graph: router → splitter →
resolver → target, with defaults synthesized for services that have no
entries (the implicit chain).  Failover legs become REAL chain targets
(compile.go rewriteFailover) so the xDS layer can emit them as
lower-priority endpoint groups, and the chain protocol resolves the
way the reference's protocol inheritance does: service-defaults beats
proxy-defaults beats the tcp default, and the presence of a router or
splitter forces http (compile.go detectCircularReferences/protocol
validation).
"""

from __future__ import annotations

from typing import Dict, List, Optional

# kinds accepted by the config-entry store; the L7 trio compiles into
# chains, the rest are stored/served for mesh-wide defaults
# (structs/config_entry.go kinds)
KINDS = ("service-router", "service-splitter", "service-resolver",
         "service-defaults", "proxy-defaults", "mesh",
         "ingress-gateway", "terminating-gateway")


def _entry(store, kind: str, name: str) -> Optional[dict]:
    return store.config_entry_get(kind, name)


def service_protocol(store, service: str) -> str:
    """Effective protocol for a service: service-defaults.protocol,
    else proxy-defaults (global) config.protocol, else tcp — the
    reference's structs.ServiceConfigEntry / ProxyConfigEntry
    inheritance."""
    sd = _entry(store, "service-defaults", service) or {}
    if sd.get("protocol"):
        return str(sd["protocol"]).lower()
    pd = _entry(store, "proxy-defaults", "global") or {}
    cfg = pd.get("config") or {}
    if cfg.get("protocol"):
        return str(cfg["protocol"]).lower()
    return "tcp"


def _add_target(chain: dict, service: str, dc: Optional[str] = None,
                subset: str = "", subset_def: Optional[dict] = None) -> str:
    """Register a chain target.  Subset targets prefix the id the way
    the reference's SNI names do (`<subset>.<service>.<ns>.<dc>` —
    connect.ServiceSNI), carrying the subset's filter/only_passing so
    endpoint resolution can apply them (ServiceResolverSubset,
    structs/config_entry_discoverychain.go:687)."""
    dc = dc or chain["Datacenter"]
    tid = f"{subset}.{service}.default.{dc}" if subset \
        else f"{service}.default.{dc}"
    tgt = {"Service": service, "Datacenter": dc}
    if subset:
        tgt["Subset"] = subset
        sd = subset_def or {}
        tgt["Filter"] = sd.get("filter", "")
        tgt["OnlyPassing"] = bool(sd.get("only_passing", False))
    chain["Targets"].setdefault(tid, tgt)
    return tid


def _resolver_node(store, service: str, chain: dict,
                   depth: int = 0, subset: str = "") -> str:
    """Build (and register in chain) the resolver node for
    (`service`, `subset`), following redirects (compile.go resolver
    handling).  Returns the node id."""
    nid = f"resolver:{subset}.{service}" if subset \
        else f"resolver:{service}"
    if nid in chain["Nodes"]:
        return nid
    if depth > 8:
        # too-deep redirect chain: terminate with a plain resolver for
        # this service rather than a dangling node reference (the
        # reference errors; a black-holed pointer is the worst option)
        target = _add_target(chain, service)
        chain["Nodes"][nid] = {"Type": "resolver", "Name": service,
                               "Target": target, "Failover": None,
                               "RedirectDepthExceeded": True}
        return nid
    res = _entry(store, "service-resolver", service) or {}
    red = res.get("redirect") or {}
    redirect = red.get("service")
    if redirect and redirect != service:
        # the redirect's own service_subset wins; else the caller's
        # requested subset follows through the indirection
        target = _resolver_node(
            store, redirect, chain, depth + 1,
            subset=red.get("service_subset") or subset)
        chain["Nodes"][nid] = {"Type": "resolver", "Name": service,
                               "Redirect": redirect, "Resolver": target,
                               # the svc's OWN entry's LB stays visible
                               # (terminating gateways read it without
                               # chasing the redirect — routes.go:71)
                               "LoadBalancer":
                                   res.get("load_balancer") or None}
        return nid
    subsets = res.get("subsets") or {}
    want_subset = subset or res.get("default_subset", "")
    if want_subset and want_subset not in subsets:
        want_subset = ""          # unknown subset: unnamed default
    target = _add_target(chain, service, subset=want_subset,
                         subset_def=subsets.get(want_subset))
    # failover legs become REAL targets: other services/subsets in
    # this dc and/or the same service in other datacenters, ordered —
    # the xDS layer emits them as priority>0 endpoint groups
    # (compile.go rewriteFailover → envoy priority failover).  The
    # map is keyed by subset; "*" is the any-subset wildcard.
    failover_targets: List[str] = []
    fo = res.get("failover")
    if isinstance(fo, dict):
        # an exact subset key OVERRIDES the "*" wildcard — the
        # wildcard covers only subsets with no explicit entry
        if want_subset in fo:
            applicable = [fo[want_subset]]
        elif "*" in fo:
            applicable = [fo["*"]]
        else:
            applicable = []
        for f in applicable:
            fsvc = f.get("service") or service
            dcs = f.get("datacenters") or []
            fres = _entry(store, "service-resolver", fsvc) or {} \
                if fsvc != service else res
            # empty service_subset → the target service's DEFAULT
            # subset (ServiceResolverFailover.ServiceSubset semantics)
            fsub = f.get("service_subset") \
                or fres.get("default_subset", "")
            if fsub not in (fres.get("subsets") or {}):
                fsub = ""
            fdef = (fres.get("subsets") or {}).get(fsub)
            if dcs:
                for dc in dcs:
                    failover_targets.append(_add_target(
                        chain, fsvc, dc, subset=fsub, subset_def=fdef))
            elif fsvc != service or fsub:
                failover_targets.append(_add_target(
                    chain, fsvc, subset=fsub, subset_def=fdef))
    chain["Nodes"][nid] = {
        "Type": "resolver", "Name": service,
        "ConnectTimeout": res.get("connect_timeout", "5s"),
        "Target": target,
        "Failover": ({"Targets": failover_targets}
                     if failover_targets else None),
        # load-balancing policy rides the resolver
        # (structs.LoadBalancer, config_entry_discoverychain.go:1097;
        # consumed by injectLBToCluster/injectLBToRouteAction)
        "LoadBalancer": res.get("load_balancer") or None,
    }
    return nid


def _splitter_node(store, service: str, chain: dict,
                   subset: str = "") -> str:
    # an EXPLICITLY requested subset pins the resolver for that subset
    # — the service's splitter applies only to unpinned traffic
    # (compile.go getSplitterOrResolverNode subset handling)
    if subset:
        return _resolver_node(store, service, chain, subset=subset)
    split = _entry(store, "service-splitter", service)
    if split is None:
        return _resolver_node(store, service, chain)
    nid = f"splitter:{service}"
    if nid in chain["Nodes"]:
        return nid
    legs = []
    for leg in split.get("splits") or []:
        svc = leg.get("service", service)
        legs.append({"Weight": leg.get("weight", 0),
                     "Node": _resolver_node(
                         store, svc, chain,
                         subset=leg.get("service_subset", ""))})
    chain["Nodes"][nid] = {"Type": "splitter", "Name": service,
                           "Splits": legs}
    return nid


def _compile_match(match: dict) -> dict:
    """One service-router route match → chain DiscoveryRoute match
    (structs.ServiceRouteHTTPMatch)."""
    headers = [{"Name": h.get("name", ""),
                "Exact": h.get("exact", ""),
                "Prefix": h.get("prefix", ""),
                "Suffix": h.get("suffix", ""),
                "Regex": h.get("regex", ""),
                "Present": bool(h.get("present", False)),
                "Invert": bool(h.get("invert", False))}
               for h in match.get("header") or []]
    query = [{"Name": q.get("name", ""),
              "Exact": q.get("exact", ""),
              "Regex": q.get("regex", ""),
              "Present": bool(q.get("present", False))}
             for q in match.get("query_param") or []]
    return {"PathPrefix": match.get("path_prefix", ""),
            "PathExact": match.get("path_exact", ""),
            "PathRegex": match.get("path_regex", ""),
            "Header": headers,
            "QueryParam": query,
            "Methods": list(match.get("methods") or [])}


def compile_chain(store, service: str, dc: str = "dc1") -> dict:
    """Compile `service`'s discovery chain (compile.go:57).

    Output shape mirrors structs.CompiledDiscoveryChain: ServiceName,
    Protocol, StartNode, Nodes (router/splitter/resolver), Targets."""
    chain: Dict = {"ServiceName": service, "Datacenter": dc,
                   "Protocol": service_protocol(store, service),
                   "Nodes": {}, "Targets": {}}
    router = _entry(store, "service-router", service)
    if router is not None:
        nid = f"router:{service}"
        routes = []
        for r in router.get("routes") or []:
            match = r.get("match") or {}
            # the reference nests the http match one level down
            # (ServiceRouteMatch.HTTP); accept both spellings, and
            # treat an explicit-null / non-dict match as empty rather
            # than wedging every proxycfg rebuild on AttributeError
            http = match.get("http") or match
            if not isinstance(http, dict):
                http = {}
            dest_def = r.get("destination") or {}
            dest = dest_def.get("service", service)
            routes.append({
                "Match": _compile_match(http),
                "Destination": {
                    "PrefixRewrite": dest_def.get("prefix_rewrite", ""),
                    "RequestTimeout": dest_def.get("request_timeout", ""),
                    "NumRetries": int(dest_def.get("num_retries", 0)),
                    "RetryOnConnectFailure": bool(
                        dest_def.get("retry_on_connect_failure", False)),
                    "RetryOnStatusCodes": list(
                        dest_def.get("retry_on_status_codes") or []),
                },
                "Node": _splitter_node(
                    store, dest, chain,
                    subset=dest_def.get("service_subset", "")),
            })
        # default catch-all to the service itself (compile.go appends
        # the implicit default route)
        routes.append({"Match": {"PathPrefix": "/"},
                       "Destination": {},
                       "Node": _splitter_node(store, service, chain)})
        chain["Nodes"][nid] = {"Type": "router", "Name": service,
                               "Routes": routes}
        chain["StartNode"] = nid
        chain["Protocol"] = "http"
    else:
        chain["StartNode"] = _splitter_node(store, service, chain)
        if f"splitter:{service}" in chain["Nodes"]:
            chain["Protocol"] = "http"
    return chain


def is_default_chain(chain: dict) -> bool:
    """True when the chain is the implicit single-resolver default with
    no redirect/failover and no L7 features — the reference's
    CompiledDiscoveryChain.IsDefault(), which gates whether the xDS
    layer emits plain upstream resources or chain resources."""
    start = chain["Nodes"].get(chain.get("StartNode", ""), {})
    targets = chain["Targets"]
    return (chain.get("Protocol", "tcp") not in ("http", "http2", "grpc")
            and start.get("Type") == "resolver"
            and start.get("Redirect") is None
            and not start.get("Failover")
            and len(targets) == 1
            and not next(iter(targets.values())).get("Subset"))


def chain_target_services(chain: dict) -> List[str]:
    """Distinct service names the chain can send traffic to (primary
    and failover targets) — the health-watch set for proxycfg."""
    seen, out = set(), []
    for t in chain["Targets"].values():
        if t["Service"] not in seen:
            seen.add(t["Service"])
            out.append(t["Service"])
    return out
