from consul_tpu.ops import gossip

__all__ = ["gossip"]
