"""Infection-style dissemination — the shared gossip kernel.

One gossip tick: every live node samples `fanout` random peers and copies
their queued item masks into its own [N, S] knowledge row.  This is the
SpMV at the heart of both membership rumors (models/swim.py) and user
events (models/events.py) — the TPU equivalent of memberlist's piggybacked
UDP gossip (reference tuning agent/config/default.go:70-84:
gossip_interval / gossip_nodes; retransmit queue lib/serf/serf.go:20-24).

TPU-first formulation: memberlist *pushes* (sender picks targets), which
tensorizes as a scatter with colliding row indices — slow on TPU.  Here
receivers *pull* from `fanout` sampled sources, which tensorizes as row
gathers (MXU/VPU-friendly, no collisions).  Push and pull epidemics have
the same expected per-tick fanout and the same exponential spread rate
(newly infected ≈ fanout·I for I ≪ N on both), and pull converges faster
in the endgame; the serving budget below reproduces push's bounded
per-node transmission count (retransmit_mult·ceil(log10 n) packets).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class GossipResult(NamedTuple):
    know: jnp.ndarray        # [N, S] bool
    sends_left: jnp.ndarray  # [N, S] int32
    newly: jnp.ndarray       # [N, S] bool — learned this tick


def disseminate(sources: jnp.ndarray, know: jnp.ndarray,
                sends_left: jnp.ndarray, sender_ok: jnp.ndarray,
                receiver_ok: jnp.ndarray, slot_active: jnp.ndarray,
                retransmit_limit: int) -> GossipResult:
    """One fanout round.

    sources: [N, G] int32 — peers each node pulls from this tick;
    sender_ok/receiver_ok: [N] bool; slot_active: [S] bool.
    """
    fanout = sources.shape[1]
    serve = know & (sends_left > 0) & sender_ok[:, None]         # [N, S]
    got = serve[sources[:, 0]]
    for g in range(1, fanout):
        got = got | serve[sources[:, g]]
    received = got & receiver_ok[:, None] & slot_active[None, :]
    newly = received & ~know
    new_know = know | newly
    # serving budget: a carrier burns `fanout` transmissions per tick while
    # queued, matching the push formulation's packet accounting
    new_sends = jnp.where(newly, retransmit_limit,
                          jnp.where(serve,
                                    jnp.maximum(sends_left - fanout, 0),
                                    sends_left))
    return GossipResult(know=new_know, sends_left=new_sends, newly=newly)
