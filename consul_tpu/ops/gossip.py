"""Infection-style dissemination as scatter ops — the shared gossip kernel.

One gossip tick: every live carrier with remaining retransmit budget picks
`fanout` random targets and sends its queued item mask; receipt is a
scatter-max OR into the [N, S] knowledge matrix.  This is the SpMV at the
heart of both membership rumors (models/swim.py) and user events
(models/events.py) — the TPU equivalent of memberlist's piggybacked UDP
gossip (reference tuning agent/config/default.go:70-84: gossip_interval /
gossip_nodes; retransmit queue lib/serf/serf.go:20-24).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class GossipResult(NamedTuple):
    know: jnp.ndarray        # [N, S] bool
    sends_left: jnp.ndarray  # [N, S] int32
    newly: jnp.ndarray       # [N, S] bool — learned this tick


def disseminate(targets: jnp.ndarray, know: jnp.ndarray,
                sends_left: jnp.ndarray, sender_ok: jnp.ndarray,
                receiver_ok: jnp.ndarray, slot_active: jnp.ndarray,
                retransmit_limit: int) -> GossipResult:
    """One fanout round.

    targets: [N, G] int32 gossip destinations per node;
    sender_ok/receiver_ok: [N] bool; slot_active: [S] bool.
    """
    n, s = know.shape
    send = know & (sends_left > 0) & sender_ok[:, None]
    got = jnp.zeros((n, s), jnp.uint8)
    send8 = send.astype(jnp.uint8)
    for g in range(targets.shape[1]):
        got = got.at[targets[:, g]].max(send8)
    received = (got > 0) & receiver_ok[:, None] & slot_active[None, :]
    newly = received & ~know
    new_know = know | newly
    new_sends = jnp.where(newly, retransmit_limit,
                          jnp.where(send,
                                    jnp.maximum(sends_left - targets.shape[1], 0),
                                    sends_left))
    return GossipResult(know=new_know, sends_left=new_sends, newly=newly)
