"""Infection-style dissemination — the shared gossip kernel.

One gossip tick: every live node pulls the queued item masks of `fanout`
ring peers at per-tick random offsets into its own [N, S] knowledge row.
This is the SpMV at the heart of both membership rumors (models/swim.py)
and user events (models/events.py) — the TPU equivalent of memberlist's
piggybacked UDP gossip (reference tuning agent/config/default.go:70-84:
gossip_interval / gossip_nodes; retransmit queue lib/serf/serf.go:20-24).

TPU-first formulation: memberlist *pushes* (sender picks targets), which
tensorizes as a scatter with colliding row indices, and a uniform random
peer per node tensorizes as a 1M-index gather — both serialize on TPU
(measured ~180 ms/tick at N=1M).  Here receivers pull from `fanout` ring
peers at shared random offsets (ops/rolls.py): the exchange is a memory
rotation (sequential HBM traffic; `ppermute` over a sharded node axis),
with the same exponential spread rate as uniform gossip — the infected
set unions `fanout` random-shifted copies of itself per tick — and the
serving budget reproduces push's bounded per-node transmission count
(retransmit_mult·ceil(log10 n) packets).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from consul_tpu.ops import rolls


class GossipResult(NamedTuple):
    know: jnp.ndarray        # [N, S] bool
    sends_left: jnp.ndarray  # [N, S] int8
    newly: jnp.ndarray       # [N, S] bool — learned this tick
    # device-side tick counters (scalar f32, summed on device so the
    # host fetches them only at sync checkpoints — never per tick).
    # served and lost share TRANSMISSION units (queued cell x ring
    # contact), so lost/served is a per-transmission loss rate:
    delivered: jnp.ndarray   # newly-learned (node, slot) cells
    served: jnp.ndarray      # cell transmissions attempted
    lost: jnp.ndarray        # cell transmissions dropped to loss


def disseminate(offsets: jnp.ndarray, know: jnp.ndarray,
                sends_left: jnp.ndarray, sender_ok: jnp.ndarray,
                receiver_ok: jnp.ndarray, slot_active: jnp.ndarray,
                retransmit_limit: int,
                p_loss: float = 0.0,
                key: Optional[jnp.ndarray] = None,
                group: Optional[jnp.ndarray] = None,
                node_ok: Optional[jnp.ndarray] = None,
                blocks: int = 1) -> GossipResult:
    """One fanout round.

    offsets: [G] int32 ring offsets shared by all nodes this tick (node i
    pulls from (i + offsets[g]) % N); sender_ok/receiver_ok: [N] bool;
    slot_active: [S] bool.

    `p_loss` (with `key`) drops whole CONTACTS: gossip piggybacks on
    one UDP packet per peer per tick, so loss is per (receiver,
    contact) — all slots in the packet vanish together (memberlist's
    gossip() sends one compound packet per selected peer).

    Nemesis hooks (chaos.py; both default None = the fast path):
    `group` [N] int partition ids — a contact only exists between
    same-group endpoints; `node_ok` [N] float32 per-node delivery
    multiplier — a contact between i and j delivers with
    (1 - p_loss) * ok_i * ok_j (degraded endpoints tax the whole
    packet, like a lossy NIC taxes every leg it carries).
    """
    fanout = offsets.shape[0]
    serve = know & (sends_left > 0) & sender_ok[:, None]         # [N, S]
    views = rolls.pull_multi(serve, offsets, blocks=blocks)
    # per-carrier queued-cell count, reduced ONCE and rotated as a 1-D
    # vector where per-contact accounting needs it — per-view [N, S]
    # reductions measurably broke the slice+mask fusion (~35%/tick).
    # Row-permutation commutes with row-wise reductions.
    cells = jnp.sum(serve, axis=1).astype(jnp.float32)           # [N]
    served = jnp.sum(cells) * fanout      # cell transmissions attempted
    lost = jnp.float32(0)
    chaotic = group is not None or node_ok is not None
    if chaotic and key is not None:
        n = know.shape[0]
        p_ok = jnp.full((n, fanout), 1.0 - p_loss, jnp.float32)
        if node_ok is not None:
            senders = jnp.stack(rolls.pull_multi(node_ok, offsets, blocks=blocks),
                                axis=1)                          # [N, G]
            p_ok = p_ok * node_ok[:, None] * senders
        ok = jax.random.uniform(key, (n, fanout)) < p_ok
        if group is not None:
            gviews = jnp.stack(rolls.pull_multi(group, offsets, blocks=blocks), axis=1)
            # a severed link is a partition, not loss: it neither
            # delivers nor counts against the loss telemetry
            ok &= gviews == group[:, None]
        carried = jnp.stack(rolls.pull_multi(cells, offsets, blocks=blocks), axis=1)
        if group is not None:
            carried = jnp.where(gviews == group[:, None], carried, 0.0)
        lost = jnp.sum(jnp.where(ok, 0.0, carried))
        views = [v & ok[:, g:g + 1] for g, v in enumerate(views)]
    elif p_loss > 0.0 and key is not None:
        ok = jax.random.bernoulli(key, 1.0 - p_loss,
                                  (know.shape[0], fanout))       # [N, G]
        # count lost in the SAME transmission units: the queued cells
        # of each dropped contact (a lost packet from a sender with
        # nothing queued never held gossip — counting it would make
        # lost incomparable to served in sparse/half-dead pools)
        carried = jnp.stack(rolls.pull_multi(cells, offsets, blocks=blocks), axis=1)
        lost = jnp.sum(jnp.where(ok, 0.0, carried))
        views = [v & ok[:, g:g + 1] for g, v in enumerate(views)]
    got = views[0]
    for v in views[1:]:
        got = got | v
    received = got & receiver_ok[:, None] & slot_active[None, :]
    newly = received & ~know
    new_know = know | newly
    # serving budget: a carrier burns `fanout` transmissions per tick while
    # queued, matching the push formulation's packet accounting
    limit = jnp.int8(retransmit_limit)
    new_sends = jnp.where(newly, limit,
                          jnp.where(serve,
                                    jnp.maximum(sends_left - jnp.int8(fanout),
                                                jnp.int8(0)),
                                    sends_left))
    return GossipResult(know=new_know, sends_left=new_sends, newly=newly,
                        delivered=jnp.sum(newly).astype(jnp.float32),
                        served=served, lost=lost)
