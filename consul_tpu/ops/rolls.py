"""Ring-shift peer exchange — the TPU-native gather replacement.

A uniform random peer per node (`x[targets]`, 1M random rows) lowers to a
serialized TPU gather: measured ~180 ms/tick at N=1M, 90x off the HBM
bandwidth bound.  Instead every node exchanges with its ring neighbor at a
per-tick random offset d: source(i) = (i + d) mod N, so the whole exchange
is one memory rotation (`roll`) — sequential HBM traffic on one chip and a
`ppermute` collective over a sharded node axis on a mesh.

Fidelity: memberlist itself walks a shuffled ring for probe targets (each
node probed ~once per round); shift-exchange keeps exactly that structure
(offset d is a bijection: every node probes once and is probed once per
round).  For dissemination, the infected set grows as the union of
`fanout` random-shifted copies of itself — the same exponential rate as
uniform push/pull gossip until saturation, completing coverage in
O(log N) rounds whp because each tick draws fresh offsets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def offsets(key, n: int, k: int) -> jnp.ndarray:
    """k nonzero ring offsets shared by all nodes this tick ([k] int32)."""
    return jax.random.randint(key, (k,), 1, n, dtype=jnp.int32)


def pull_multi(mat: jnp.ndarray, offsets, blocks: int = 1) -> list:
    """k ring views: out[g][i] = mat[(i + offsets[g]) % N].  Offsets may
    be traced.  `blocks` is a LOWERING hint, never a semantic one — the
    result is the exact rotation for any value (so a sharded run's
    trajectory is bit-identical to single-device; tests/test_sharding).

    blocks == 1 (single device): dynamic slices over one doubled
    buffer shared by every view — sequential HBM traffic, no gather.

    blocks == device count (node axis sharded over a mesh): the naive
    doubled-buffer slice at a TRACED offset makes GSPMD all-gather the
    whole [2N, ...] buffer onto every device (the slice window spans
    every shard).  Instead the rotation is decomposed as
    d = s * L + r (L = N / blocks): the block-level rotation by s runs
    as log2(blocks) STATIC rolls on the sharded axis (each a
    collective-permute of the local shard, selected by s's bits), and
    the residual r becomes a dynamic slice along the UNSHARDED axis of
    a [blocks, 2L, ...] per-block doubled buffer — so cross-shard
    rumor/probe traffic lowers to neighbor collectives and per-device
    traffic stays O(L log blocks), never O(N)."""
    n = mat.shape[0]
    if blocks <= 1 or n % blocks:
        doubled = jnp.concatenate([mat, mat], axis=0)
        return [jax.lax.dynamic_slice_in_dim(
            doubled, jnp.asarray(offsets[g], jnp.int32) % n, n, axis=0)
            for g in range(len(offsets))]
    ell = n // blocks
    m = mat.reshape((blocks, ell) + mat.shape[1:])
    out = []
    for g in range(len(offsets)):
        d = jnp.asarray(offsets[g], jnp.int32) % n
        s, r = d // ell, d % ell
        rot = m
        step = 1
        while step < blocks:
            shifted = jnp.roll(rot, -step, axis=0)   # static: ppermute
            rot = jnp.where((s // step) % 2 == 1, shifted, rot)
            step *= 2
        # out[a, p] = m[a+s, p+r] while p+r < L, else m[a+s+1, p+r-L]:
        # pair each block with its successor and slice locally at r
        nxt = jnp.roll(rot, -1, axis=0)
        doubled = jnp.concatenate([rot, nxt], axis=1)
        out.append(jax.lax.dynamic_slice_in_dim(doubled, r, ell, axis=1)
                   .reshape(mat.shape))
    return out


def pull(mat: jnp.ndarray, d, blocks: int = 1) -> jnp.ndarray:
    """Row view from each node's ring peer: out[i] = mat[(i + d) % N]."""
    return pull_multi(mat, [d], blocks=blocks)[0]


def push(mat: jnp.ndarray, d, blocks: int = 1) -> jnp.ndarray:
    """Inverse view: out[j] = mat[(j - d) % N] — what node j receives when
    every node i sends to (i + d) % N."""
    n = mat.shape[0]
    d = jnp.asarray(d, jnp.int32) % n
    return pull(mat, n - d, blocks=blocks)
