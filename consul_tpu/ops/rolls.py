"""Ring-shift peer exchange — the TPU-native gather replacement.

A uniform random peer per node (`x[targets]`, 1M random rows) lowers to a
serialized TPU gather: measured ~180 ms/tick at N=1M, 90x off the HBM
bandwidth bound.  Instead every node exchanges with its ring neighbor at a
per-tick random offset d: source(i) = (i + d) mod N, so the whole exchange
is one memory rotation (`roll`) — sequential HBM traffic on one chip and a
`ppermute` collective over a sharded node axis on a mesh.

Fidelity: memberlist itself walks a shuffled ring for probe targets (each
node probed ~once per round); shift-exchange keeps exactly that structure
(offset d is a bijection: every node probes once and is probed once per
round).  For dissemination, the infected set grows as the union of
`fanout` random-shifted copies of itself — the same exponential rate as
uniform push/pull gossip until saturation, completing coverage in
O(log N) rounds whp because each tick draws fresh offsets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def offsets(key, n: int, k: int) -> jnp.ndarray:
    """k nonzero ring offsets shared by all nodes this tick ([k] int32)."""
    return jax.random.randint(key, (k,), 1, n, dtype=jnp.int32)


def pull_multi(mat: jnp.ndarray, offsets) -> list:
    """k ring views sharing ONE doubled buffer: out[g][i] =
    mat[(i + offsets[g]) % N].  Offsets may be traced.  Lowers to
    dynamic slices over the doubled buffer — sequential HBM traffic, no
    gather (and one copy of the lowering for every caller)."""
    n = mat.shape[0]
    doubled = jnp.concatenate([mat, mat], axis=0)
    out = []
    for g in range(len(offsets)):
        d = jnp.asarray(offsets[g], jnp.int32) % n
        out.append(jax.lax.dynamic_slice_in_dim(doubled, d, n, axis=0))
    return out


def pull(mat: jnp.ndarray, d) -> jnp.ndarray:
    """Row view from each node's ring peer: out[i] = mat[(i + d) % N]."""
    return pull_multi(mat, [d])[0]


def push(mat: jnp.ndarray, d) -> jnp.ndarray:
    """Inverse view: out[j] = mat[(j - d) % N] — what node j receives when
    every node i sends to (i + d) % N."""
    n = mat.shape[0]
    d = jnp.asarray(d, jnp.int32) % n
    return pull(mat, n - d)
