"""Set-reconciliation kernel: sorted-merge diff of two keyed tables.

The tensor replacement for the reference's per-entry map walk in
`local.updateSyncState` (agent/local/state.go:880-1051), which diffs the
agent's desired services/checks against the server catalog and emits
register/deregister deltas.  Here both sides are id-sorted columnar tables
and the diff is two vectorized binary-search joins — O((M+K) log K) work
with no data-dependent shapes, so it scales to the 1M-service config of
BASELINE.json on one chip.

Invalid rows carry id = INT32_MAX so they sort to the tail and never match.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

INVALID_ID = jnp.int32(2**31 - 1)


class DiffResult(NamedTuple):
    push: jnp.ndarray   # [M] bool: src rows missing or stale in dst (register/update)
    drop: jnp.ndarray   # [K] bool: dst rows absent from src (deregister)


def diff_sorted(src_ids: jnp.ndarray, src_ver: jnp.ndarray,
                dst_ids: jnp.ndarray, dst_ver: jnp.ndarray) -> DiffResult:
    """Reconcile desired (src) against actual (dst); both id-ascending.

    A src row is `push` when its id is absent from dst or present with a
    different version (the reference compares full structs; versions stand
    in for content hashes).  A dst row is `drop` when its id left src.
    """
    k = dst_ids.shape[0]
    pos = jnp.clip(jnp.searchsorted(dst_ids, src_ids), 0, k - 1)
    hit = (dst_ids[pos] == src_ids) & (src_ids != INVALID_ID)
    stale = hit & (dst_ver[pos] != src_ver)
    push = (src_ids != INVALID_ID) & (~hit | stale)

    m = src_ids.shape[0]
    rpos = jnp.clip(jnp.searchsorted(src_ids, dst_ids), 0, m - 1)
    rhit = (src_ids[rpos] == dst_ids) & (dst_ids != INVALID_ID)
    drop = (dst_ids != INVALID_ID) & ~rhit
    return DiffResult(push=push, drop=drop)


def apply_push(src_ids, src_ver, dst_ids, dst_ver, push: jnp.ndarray,
               capacity_ok: bool = True):
    """Merge pushed src rows into dst, keeping dst id-sorted.

    Concatenate + sort by (id, source-priority) then dedup: the pushed copy
    wins over a stale dst copy.  Returns new (dst_ids, dst_ver) with the
    same capacity K (overflow rows beyond K are dropped — callers size K
    ≥ live set, mirroring the watch-limit style capacity bounds of the
    reference, state_store.go:87-97)."""
    k = dst_ids.shape[0]
    cand_ids = jnp.where(push, src_ids, INVALID_ID)
    all_ids = jnp.concatenate([cand_ids, dst_ids])
    all_ver = jnp.concatenate([src_ver, dst_ver])
    # source-priority: pushed rows (index < M) win ties
    prio = jnp.concatenate([jnp.zeros_like(cand_ids), jnp.ones_like(dst_ids)])
    order = jnp.lexsort((prio, all_ids))
    sids, sver = all_ids[order], all_ver[order]
    first = jnp.concatenate([jnp.array([True]), sids[1:] != sids[:-1]])
    sids = jnp.where(first, sids, INVALID_ID)
    # compact: stable sort invalids to the tail, keep first K
    order2 = jnp.argsort(jnp.where(sids == INVALID_ID, 1, 0), stable=True)
    sids, sver = sids[order2], sver[order2]
    return sids[:k], sver[:k]
