"""User-facing snapshot archives: tar.gz + SHA-256 + raft metadata.

The reference's durable snapshot artifact (snapshot/snapshot.go:164 Read,
archive.go write/read): a gzipped tar holding `meta.json` (raft index/
term/version), `state.bin` (the FSM image), and `SHA256SUMS`; restore
verifies the sums before touching state, and a successful restore
abandons the old store so every blocked query wakes
(state_store.go:106-112 AbandonCh; here the store's index bump + coarse
waiter wake carries that role).
"""

from __future__ import annotations

import hashlib
import io
import json
import tarfile
import time
import zlib
from typing import Optional, Tuple

from consul_tpu.version import VERSION


class SnapshotError(Exception):
    pass


def write_archive(state: dict, index: int = 0, term: int = 0) -> bytes:
    """Serialize a store image into the tar.gz archive format."""
    state_bin = json.dumps(state, sort_keys=True).encode()
    meta = json.dumps({
        "Version": VERSION, "Index": index, "Term": term,
        "CreatedAt": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }, sort_keys=True).encode()
    sums = (f"{hashlib.sha256(meta).hexdigest()}  meta.json\n"
            f"{hashlib.sha256(state_bin).hexdigest()}  state.bin\n"
            ).encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for name, data in (("meta.json", meta), ("state.bin", state_bin),
                           ("SHA256SUMS", sums)):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def read_archive(blob: bytes) -> Tuple[dict, dict]:
    """(state, meta) after integrity verification; raises SnapshotError
    on a corrupt or tampered archive (snapshot.go Verify)."""
    # Decompression errors can surface at open() (bad gzip header), at
    # getmembers() (bad tar header), or at read() (gzip CRC trailer) —
    # all three must map to SnapshotError, so the whole walk sits inside
    # one handler.  zlib.error covers truncated deflate streams that
    # escape the gzip wrapper.
    members = {}
    try:
        with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
            for m in tar.getmembers():
                f = tar.extractfile(m)
                if f is not None:
                    members[m.name] = f.read()
    except (tarfile.TarError, OSError, EOFError, zlib.error) as e:
        raise SnapshotError(f"not a snapshot archive: {e}")
    for required in ("meta.json", "state.bin", "SHA256SUMS"):
        if required not in members:
            raise SnapshotError(f"archive missing {required}")
    sums = {}
    for line in members["SHA256SUMS"].decode().splitlines():
        digest, _, name = line.partition("  ")
        if name:
            sums[name] = digest
    for name in ("meta.json", "state.bin"):
        want = sums.get(name)
        got = hashlib.sha256(members[name]).hexdigest()
        if want != got:
            raise SnapshotError(
                f"checksum mismatch for {name}: archive corrupt")
    meta = json.loads(members["meta.json"])
    state = json.loads(members["state.bin"])
    return state, meta


def inspect(blob: bytes) -> dict:
    """`consul snapshot inspect` fields (command/snapshot/inspect)."""
    state, meta = read_archive(blob)
    return {"Meta": meta, "SizeBytes": len(blob),
            "Tables": {k: len(v) if isinstance(v, (dict, list)) else 1
                       for k, v in state.items()}}
