"""Prepared queries: stored service lookups with templates + DC failover.

The reference's PreparedQuery endpoint (agent/consul/prepared_query_endpoint.go:
341 Execute, :477 ExecuteRemote) and template engine
(agent/consul/prepared_query/template.go).  A query definition:

    {"name": "...", "service": {"service": "web", "tags": [...],
     "only_passing": bool, "near": "<node>|_agent",
     "failover": {"nearest_n": 2, "datacenters": ["dc2", ...]}},
     "template": {"type": "name_prefix_match", "regexp": "..."},
     "dns": {"ttl": "10s"}}

Execution (Execute, :341): resolve by id or name — falling back to
template match on the name — look up healthy instances, filter by tags,
RTT-sort from the near-node, and when the local DC has no instances walk
the failover DC list (nearest_n by WAN coordinate distance first, then
the explicit list — querySetLimit/queryFailover, :600-700 region).

Template interpolation supports ${name.full}, ${name.prefix},
${name.suffix}, and ${match(N)} regex groups (template.go).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

TEMPLATE_NAME_PREFIX = "name_prefix_match"


class QueryError(Exception):
    pass


def _interp(text: str, name: str, prefix: str,
            groups: List[str]) -> str:
    """Template variable interpolation (template.go renderTemplate)."""

    def sub(m):
        var = m.group(1).strip()
        if var == "name.full":
            return name
        if var == "name.prefix":
            return prefix
        if var == "name.suffix":
            return name[len(prefix):]
        gm = re.fullmatch(r"match\((\d+)\)", var)
        if gm:
            i = int(gm.group(1))
            return groups[i] if i < len(groups) else ""
        return ""

    return re.sub(r"\$\{([^}]*)\}", sub, text)


def resolve(store, name_or_id: str) -> Optional[dict]:
    """Find a query by id, exact name, or template match; template queries
    are rendered against the looked-up name (prepared_query_endpoint.go
    ExecuteRemote resolve + template apply)."""
    q = store.query_get(name_or_id) or store.query_get_by_name(name_or_id)
    if q is not None:
        if not q.get("template"):
            return q
        # direct hit on a template (by id or exact name): render against
        # the given lookup string so no raw ${...} ever leaks into a
        # service lookup (the reference renders with empty matches here)
        prefix = q.get("name", "")
        if not name_or_id.startswith(prefix):
            prefix = ""
        return _render(q, name_or_id, prefix, [])
    # template search: longest matching name_prefix_match, else regexp
    best = None
    for cand in store.query_list():
        tpl = cand.get("template")
        if not tpl:
            continue
        ttype = tpl.get("type", TEMPLATE_NAME_PREFIX)
        if ttype == TEMPLATE_NAME_PREFIX:
            prefix = cand.get("name", "")
            if name_or_id.startswith(prefix):
                if best is None or len(prefix) > len(best[1]):
                    best = (cand, prefix, [])
        elif ttype == "regexp":
            try:
                m = re.match(tpl.get("regexp", "$^"), name_or_id)
            except re.error:
                continue  # a bad stored regexp must not poison resolution
            if m and best is None:
                best = (cand, cand.get("name", ""), [m.group(0),
                                                     *m.groups()])
    if best is None:
        return None
    cand, prefix, groups = best
    return _render(cand, name_or_id, prefix, groups)


def _render(q: dict, name: str, prefix: str, groups: List[str]) -> dict:
    import copy
    out = copy.deepcopy(q)

    def walk(obj):
        if isinstance(obj, str):
            return _interp(obj, name, prefix, groups)
        if isinstance(obj, list):
            return [walk(x) for x in obj]
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        return obj

    svc = out.get("service") or {}
    out["service"] = walk(svc)
    return out


class QueryExecutor:
    """Executes prepared queries against (store, oracle) with DC failover.

    `remote_execute(dc, query, limit)` is the cross-DC hook (ExecuteRemote
    :477) — wired by the multi-DC layer; `dc_order()` ranks failover DCs
    by WAN distance (router.GetDatacentersByDistance)."""

    def __init__(self, store, oracle=None, node_name: str = "node0",
                 dc: str = "dc1",
                 remote_execute: Optional[Callable] = None,
                 dc_order: Optional[Callable[[], List[str]]] = None):
        self.store = store
        self.oracle = oracle
        self.node_name = node_name
        self.dc = dc
        self.remote_execute = remote_execute
        self.dc_order = dc_order

    # ------------------------------------------------------------- execute

    def execute(self, name_or_id: str, limit: int = 0,
                near: Optional[str] = None) -> Optional[dict]:
        """Execute → {"Service", "Nodes", "DNS", "Datacenter",
        "Failovers"}; None when the query doesn't resolve (DNS answers
        NXDOMAIN)."""
        q = resolve(self.store, name_or_id)
        if q is None:
            return None
        svc = q.get("service") or {}
        service = svc.get("service", "")
        rows = self._local_rows(svc)
        failovers = 0
        result_dc = self.dc
        if not rows:
            rows, result_dc, failovers = self._failover(q, svc)
        rows = self._sort(rows, near or svc.get("near"))
        if limit:
            rows = rows[:limit]
        return {"Service": service, "Datacenter": result_dc,
                "Failovers": failovers, "Nodes": rows,
                "DNS": q.get("dns") or {}}

    def execute_resolved(self, query: dict) -> List[dict]:
        """Run an already-resolved query's service lookup locally — the
        receiving side of cross-DC failover (ExecuteRemote :477)."""
        svc = query.get("service") or {}
        return self._sort(self._local_rows(svc), svc.get("near"))

    def _local_rows(self, svc: dict) -> List[dict]:
        service = svc.get("service", "")
        tags = [t for t in (svc.get("tags") or []) if not t.startswith("!")]
        banned = [t[1:] for t in (svc.get("tags") or [])
                  if t.startswith("!")]
        rows = self.store.health_service_nodes(
            service, passing_only=bool(svc.get("only_passing")))
        out = []
        for r in rows:
            s = r["service"] if isinstance(r, dict) and "service" in r else r
            row_tags = s.get("tags", [])
            if any(t not in row_tags for t in tags):
                continue
            if any(t in row_tags for t in banned):
                continue
            # non-passing-only still drops critical (health filter)
            checks = r.get("checks", []) if isinstance(r, dict) else []
            if any(c["status"] == "critical" for c in checks):
                continue
            out.append(s)
        return out

    def _failover(self, q: dict, svc: dict):
        """Walk failover DCs: nearest_n by WAN distance, then explicit
        list, dedup preserving order (queryFailover)."""
        fo = svc.get("failover") or {}
        dcs: List[str] = []
        n = int(fo.get("nearest_n", 0))
        if n > 0 and self.dc_order is not None:
            for d in self.dc_order()[:n + 1]:
                if d != self.dc:
                    dcs.append(d)
            dcs = dcs[:n]
        for d in fo.get("datacenters") or []:
            if d != self.dc and d not in dcs:
                dcs.append(d)
        failovers = 0
        if self.remote_execute is None:
            return [], self.dc, len(dcs)
        for d in dcs:
            failovers += 1
            try:
                rows = self.remote_execute(d, q)
            except Exception:
                continue
            if rows:
                return rows, d, failovers
        return [], self.dc, failovers

    def _sort(self, rows: List[dict], near: Optional[str]) -> List[dict]:
        origin = self.node_name if near in (None, "", "_agent") else near
        if self.oracle is None:
            return rows
        try:
            order = self.oracle.sort_by_rtt(origin,
                                            [r["node"] for r in rows])
            pos = {n: i for i, n in enumerate(order)}
            return sorted(rows, key=lambda r: pos.get(r["node"], 1 << 30))
        except (KeyError, IndexError):
            return rows
