"""Debug capture: one archive of everything an operator needs.

The reference's `consul debug` (command/debug/debug.go:288-496) captures
pprof profiles, metrics, logs, and host info into a tar archive over a
sampling window.  Python has no pprof; the equivalents here are thread
stack dumps (the goroutine-dump analogue), the telemetry registry,
recent log lines, agent self/members, and host info — tarred with the
same capture-window layout.

Also home to the thread-leak checker (goleak analogue — the reference's
agent/routine-leak-checker/leak_test.go asserts a full agent shutdown
leaves no goroutines), used by tests and `consul-tpu debug`.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tarfile
import threading
import time
import traceback
from typing import Dict, List, Optional


def thread_dump() -> str:
    """All live thread stacks (the goroutine profile analogue)."""
    out = []
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.append(f"--- {t.name} (daemon={t.daemon}, "
                   f"alive={t.is_alive()}) ---")
        frame = frames.get(t.ident)
        if frame is not None:
            out.extend(line.rstrip() for line in
                       traceback.format_stack(frame))
    return "\n".join(out)


def sample_profile(seconds: float = 1.0,
                   interval: float = 0.01) -> dict:
    """Statistical CPU profile across ALL threads: sample
    sys._current_frames() every `interval`, aggregate by
    (file, line, function).  The /debug/pprof/profile analogue —
    cProfile only sees the calling thread, which is useless for a
    threaded server; wall-clock sampling sees every thread."""
    counts: Dict[str, int] = {}
    samples = 0
    deadline = time.monotonic() + max(0.05, seconds)
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue    # the sampler itself is noise
            f = frame
            key = (f"{f.f_code.co_filename}:{f.f_lineno} "
                   f"{f.f_code.co_name}")
            counts[key] = counts.get(key, 0) + 1
        samples += 1
        time.sleep(interval)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:50]
    return {"Seconds": seconds, "Samples": samples,
            # AvgThreads: mean number of threads observed at the site
            # per sweep (can exceed 1.0 when several threads share it)
            "Top": [{"Site": site, "Count": c,
                     "AvgThreads": c / max(1, samples)}
                    for site, c in top]}


_tracemalloc_started = False


def heap_snapshot(top: int = 30) -> dict:
    """Allocation snapshot via tracemalloc (the heap profile analogue).
    First call starts tracing — deltas show up from the second call."""
    global _tracemalloc_started
    import tracemalloc
    if not _tracemalloc_started:
        tracemalloc.start()
        _tracemalloc_started = True
        return {"Started": True, "Top": []}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    return {"Started": False,
            "Top": [{"Site": str(s.traceback[0]), "SizeBytes": s.size,
                     "Count": s.count} for s in stats]}


def host_info() -> dict:
    """Host facts (agent/debug/host.go's gopsutil capture, stdlib-only)."""
    info = {"platform": sys.platform, "python": sys.version,
            "pid": os.getpid(), "cpu_count": os.cpu_count()}
    try:
        la = os.getloadavg()
        info["loadavg"] = {"1m": la[0], "5m": la[1], "15m": la[2]}
    except (OSError, AttributeError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        info["max_rss_kb"] = ru.ru_maxrss
    except ImportError:
        pass
    return info


def capture(agent=None, intervals: int = 2,
            interval_s: float = 0.5) -> bytes:
    """Sampled debug archive (debug.go capture loop): per-interval
    metrics (JSON + prometheus exposition) + thread dumps, plus
    one-shot host/agent/log captures, the trace-span ring buffer, the
    flight-recorder event journal (events.jsonl), and the tick
    profiler's EMA table (profile.json)."""
    from consul_tpu import flight, telemetry, trace
    from consul_tpu.logging import default_buffer
    from consul_tpu.profiler import default_profiler

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        def add(name: str, data: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))

        add("host.json", json.dumps(host_info(), indent=2).encode())
        add("logs.txt", "\n".join(default_buffer().recent()).encode())
        if agent is not None:
            # pull the device-side sim counters into the registry so
            # the metrics snapshots below carry consul.serf.* too
            if hasattr(agent.oracle, "publish_sim_metrics"):
                try:
                    agent.oracle.publish_sim_metrics()
                except Exception:
                    pass
            add("agent.json", json.dumps({
                "node_name": agent.node_name,
                "members_summary": agent.oracle.members_summary(),
                "catalog_index": agent.store.index,
            }, indent=2).encode())
        for i in range(intervals):
            reg = telemetry.default_registry()
            add(f"{i}/metrics.json", json.dumps(
                reg.dump(), indent=2).encode())
            add(f"{i}/metrics.prom", reg.prometheus().encode())
            add(f"{i}/threads.txt", thread_dump().encode())
            if i < intervals - 1:
                time.sleep(interval_s)
        # the mesh-control-plane table (ISSUE 16): the agent's
        # per-proxy rebuild/push SLI rows; empty without an agent (the
        # section always exists so bundle consumers need no probing)
        xds_rows: list = []
        if agent is not None:
            try:
                api = getattr(agent, "api", None)
                if api is not None:
                    xds_rows = api.proxycfg.table()
            except Exception:
                pass
        add("xds.json", json.dumps({"proxies": xds_rows},
                                   indent=2).encode())
        # the rings LAST: they then include spans/events recorded
        # during the capture window itself
        add("trace.json", json.dumps(trace.dump(), indent=2).encode())
        add("events.jsonl", flight.default_recorder().dump_jsonl())
        add("profile.json", json.dumps(default_profiler().snapshot(),
                                       indent=2).encode())
    return buf.getvalue()


class ThreadLeakChecker:
    """goleak analogue: snapshot live threads, later assert no leaks.

    Usage (tests):
        chk = ThreadLeakChecker()
        agent = Agent(...); agent.start(); agent.stop()
        chk.assert_no_leaks()
    """

    def __init__(self):
        self._before = {t.ident for t in threading.enumerate()}

    def leaked(self, grace_s: float = 3.0) -> List[threading.Thread]:
        """Threads alive now that weren't at construction, after letting
        shutdowns drain for up to `grace_s`."""
        deadline = time.time() + grace_s
        while time.time() < deadline:
            extra = [t for t in threading.enumerate()
                     if t.ident not in self._before and t.is_alive()]
            if not extra:
                return []
            time.sleep(0.1)
        return [t for t in threading.enumerate()
                if t.ident not in self._before and t.is_alive()]

    def assert_no_leaks(self, grace_s: float = 3.0) -> None:
        extra = self.leaked(grace_s)
        if extra:
            names = ", ".join(t.name for t in extra)
            raise AssertionError(f"leaked threads: {names}")
