"""The storage seam: every durability-relevant file operation funnels
through one object so the storage nemesis can sit between the code and
the disk.

Consul's durability story leans on a small set of primitives — append +
fsync on the WAL (raft-boltdb's bolt file), tmp-write + rename + dir
fsync for atomic metadata (FileSnapshotStore), and nothing else.  Those
primitives are exactly where disks betray you: torn appends, fsyncs
that fail or silently lie, renames that hit the journal before the data
they name, ENOSPC mid-record.  `StorageOps` is the honest
implementation; `consul_tpu.chaos.FaultyStorage` implements the same
interface over a simulated page-cache/durable split and injects those
betrayals deterministically.

The seam is enforced: `tools/storage_audit.py` fails the build if any
`consul_tpu/` code calls `os.fsync`/`os.replace` outside this module —
an I/O call the nemesis can't intercept is an I/O call the crash-point
harness can't prove safe.
"""

from __future__ import annotations

import os
import tempfile
from typing import BinaryIO, Tuple


class StorageOps:
    """Real-disk implementation of the seam.  One shared instance
    (`OS`) serves every caller; the methods are stateless."""

    # ------------------------------------------------------------ handles

    def open_append(self, path: str) -> BinaryIO:
        return open(path, "ab")

    def open_read(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_rw(self, path: str) -> BinaryIO:
        return open(path, "r+b")

    def create_tmp(self, directory: str,
                   prefix: str) -> Tuple[BinaryIO, str]:
        """A unique scratch file in `directory` (same filesystem, so a
        later replace() is an atomic rename)."""
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=prefix)
        return os.fdopen(fd, "wb"), tmp

    # ------------------------------------------------------- durable ops

    def write(self, f: BinaryIO, data: bytes) -> None:
        f.write(data)

    def fsync(self, f: BinaryIO) -> None:
        """Flush + fsync: the only call that makes bytes durable."""
        f.flush()
        os.fsync(f.fileno())

    def truncate(self, f: BinaryIO, size: int) -> None:
        f.truncate(size)

    def replace(self, src: str, dst: str) -> None:
        """Atomic rename.  NOT durable until fsync_dir() on the parent
        — a crash in between may undo it (or, on reordering disks,
        keep the name but lose the renamed file's data; the WAL layer
        defends with checksums + a previous-generation fallback)."""
        os.replace(src, dst)

    def fsync_dir(self, directory: str) -> None:
        """Make preceding renames in `directory` durable."""
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    # -------------------------------------------------------- inspection

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def unlink(self, path: str) -> None:
        os.unlink(path)


OS = StorageOps()


def atomic_replace(path: str, data: bytes, sync: bool = False,
                   ops: StorageOps = None) -> None:
    """tmp-write + rename for the config/state persisters (agent local
    state, ACL tokens, auto-config bootstrap, built native objects):
    readers see the old file or the new file, never a torn middle.
    `sync=True` adds the fsync + dir-fsync pair for files that must
    survive power loss, not just process death."""
    io = ops or OS
    d = os.path.dirname(path) or "."
    f, tmp = io.create_tmp(d, ".tmp-")
    try:
        with f:
            io.write(f, data)
            if sync:
                io.fsync(f)
        io.replace(tmp, path)
        if sync:
            io.fsync_dir(d)
    except BaseException:
        try:
            io.unlink(tmp)
        except OSError:
            pass
        raise
