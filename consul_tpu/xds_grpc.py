"""gRPC ADS server: the protobuf control plane a stock Envoy attaches to.

Implements envoy.service.discovery.v3.AggregatedDiscoveryService — both
StreamAggregatedResources (state-of-the-world) and
DeltaAggregatedResources (incremental) — over real gRPC (grpcio), with
the generated envoy v3 protos on the wire (consul_tpu/xds_pb).  This is
the reference's agent/xds/server.go:186 (NewServer + Register) and
agent/xds/delta.go:33 (DeltaAggregatedResources) role.

Session shape (delta.go / sotw semantics):

  * The client identifies its proxy via `node.id` on the first request
    (Consul's envoy bootstrap sets node.id to the sidecar service id).
  * Each resource type is an independent subscription on the shared
    stream; the server pushes a response whenever the proxy's config
    snapshot version moves past what that type last saw.
  * An ACK echoes the response nonce with no error_detail; a NACK
    carries error_detail — the server logs it and waits for the next
    snapshot rather than re-sending the rejected config (xds server
    backoff stance).
  * ACLs: requests may carry `x-consul-token` metadata; when an
    authorize callback is installed the token must grant service:write
    on the proxied service (the reference resolves the token the same
    way on stream start).
"""

from __future__ import annotations

import logging
import queue
import threading
from concurrent import futures
from typing import Callable, Dict, List, Optional

import grpc

from consul_tpu import locks
from consul_tpu import xds as xdsmod
from consul_tpu import xds_pb

log = logging.getLogger("consul_tpu.xds_grpc")

SERVICE = "envoy.service.discovery.v3.AggregatedDiscoveryService"

# ADS makes ordering explicit: clusters before endpoints before
# listeners before routes, so a pushed config never references a
# resource the client doesn't hold yet (delta.go orders the same way)
GROUP_BY_URL = {url: group for group, url in xdsmod.TYPE_URLS.items()}
URL_ORDER = [xdsmod.TYPE_URLS[g]
             for g in ("clusters", "endpoints", "listeners", "routes")]


class _StreamState:
    """Per-stream bookkeeping shared by both protocol variants."""

    def __init__(self):
        self.proxy_id: Optional[str] = None
        self.watch = None                 # ProxyState
        self.nonce = 0
        # type_url -> (sent_version:int, nonce:str, names:tuple)
        self.sent: Dict[str, tuple] = {}

    def next_nonce(self) -> str:
        self.nonce += 1
        return str(self.nonce)


def _filter_names(resources: List[dict], names) -> List[dict]:
    if not names:
        return resources
    wanted = set(names)
    return [r for r in resources
            if xds_pb.resource_name(r) in wanted]


class AdsServicer:
    """One servicer per agent, backed by the proxycfg Manager."""

    def __init__(self, manager,
                 authorize: Optional[Callable[[str, str], bool]] = None,
                 poll_interval: float = 30.0):
        self.manager = manager
        self.authorize = authorize
        self.poll_interval = poll_interval
        # one generated payload per snapshot OBJECT: four type pushes
        # per update (and every stream on the same proxy) share it
        # instead of regenerating the full resource set.  Keyed weakly
        # on the snapshot itself — (proxy_id, version) tuples would
        # collide when a proxy deregisters and re-registers (the new
        # ProxyState restarts version numbering), serving the OLD
        # registration's config; the weak map can't collide and GC
        # evicts entries exactly when their snapshot is replaced.
        import weakref
        self._payload_lock = locks.make_lock("xds.payload")
        # snapshot object -> generated resource payload  # guarded-by: _payload_lock
        self._payload_cache = weakref.WeakKeyDictionary()
        locks.register_guards(self, self._payload_lock,
                              "_payload_cache")

    def _payload(self, st: "_StreamState", snap) -> dict:
        with self._payload_lock:
            hit = self._payload_cache.get(snap)
            if hit is not None:
                return hit
        payload = xdsmod.snapshot_resources(snap)["Resources"]
        with self._payload_lock:
            self._payload_cache[snap] = payload
        return payload

    # ------------------------------------------------------------ plumbing

    def _resolve(self, st: _StreamState, node, context):
        """Bind the stream to a proxy on the first request carrying a
        node id; abort on unknown proxies or denied tokens."""
        if st.proxy_id is not None:
            return True
        pid = node.id if node is not None else ""
        if not pid:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "node.id required (proxy service id)")
        # version gate BEFORE serving any resource: an unsupported
        # envoy build announced in node metadata fails the stream with
        # the reason (envoy_versioning.go, server.go:360)
        from consul_tpu import envoy_versioning
        reason = envoy_versioning.check_supported(node)
        if reason is not None:
            logging.getLogger("consul_tpu.xds").warning(
                "rejecting ADS stream from %s: %s", pid, reason)
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, reason)
        watch = self.manager.watch(pid)
        if watch is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"unknown proxy service id {pid!r}")
        if self.authorize is not None:
            md = dict(context.invocation_metadata() or ())
            token = md.get("x-consul-token", "")
            svc = watch.svc.get("name", pid)
            if not self.authorize(token, svc):
                context.abort(grpc.StatusCode.PERMISSION_DENIED,
                              "service:write denied")
        st.proxy_id = pid
        st.watch = watch
        return True

    def _reader(self, request_iterator, q: "queue.Queue"):
        try:
            for req in request_iterator:
                q.put(("req", req))
        except Exception:
            pass
        finally:
            q.put(("eof", None))

    def _watcher(self, st: _StreamState, q: "queue.Queue",
                 stop: threading.Event):
        """Post a token whenever the proxy snapshot version moves.

        Fetches in short slices (not poll_interval-long blocks) so the
        thread notices stop.set() within ~1s of stream close instead of
        pinning the ProxyState for up to poll_interval."""
        version = 0
        slice_s = min(1.0, self.poll_interval)
        while not stop.is_set():
            watch = st.watch
            if not watch.alive():
                # terminal state (ISSUE 19 satellite): the proxy
                # deregistered or its registration was replaced.
                # Rebind to the replacement if one exists; otherwise
                # end the stream promptly (Envoy reconnects) instead
                # of hot-spinning on a dead state's instant fetches.
                rebound = self.manager.watch(st.proxy_id)
                if rebound is None:
                    q.put(("eof", None))
                    return
                st.watch = watch = rebound
            snap = watch.fetch(version, timeout=slice_s)
            if snap is None:
                continue
            if snap.version > version:
                version = snap.version
                q.put(("update", version))

    # ----------------------------------------------------- state of world

    def stream_aggregated_resources(self, request_iterator, context):
        st = _StreamState()
        q: "queue.Queue" = queue.Queue()
        stop = threading.Event()
        threading.Thread(target=self._reader,
                         args=(request_iterator, q), daemon=True).start()
        watcher: Optional[threading.Thread] = None
        try:
            while True:
                kind, item = q.get()
                if kind == "eof":
                    return
                if kind == "req":
                    req = item
                    self._resolve(st, req.node, context)
                    if watcher is None:
                        watcher = threading.Thread(
                            target=self._watcher, args=(st, q, stop),
                            daemon=True)
                        watcher.start()
                    url = req.type_url
                    if url not in GROUP_BY_URL:
                        context.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            f"unknown type_url {url!r}")
                    prev = st.sent.get(url)
                    names = tuple(req.resource_names)
                    if req.error_detail.code:
                        # NACK: keep what we sent; next snapshot retries
                        log.warning(
                            "xds NACK proxy=%s type=%s: %s",
                            st.proxy_id, url,
                            req.error_detail.message)
                        self._note_nack(st, url,
                                        req.error_detail.message)
                        continue
                    if prev is not None and \
                            req.response_nonce == prev[1] and \
                            names == prev[2]:
                        continue        # pure ACK: wait for changes
                    yield from self._push(st, [url], names_override={
                        url: names})
                elif kind == "update":
                    yield from self._push(
                        st, [u for u in URL_ORDER if u in st.sent])
        finally:
            stop.set()

    @staticmethod
    def _note_nack(st: _StreamState, url: str, detail: str) -> None:
        """NACK SLIs (ISSUE 16): the consul.xds.nacks{type} counter
        and an xds.push.nack flight event — a rejected config is
        exactly the kind of rare, load-bearing fact the journal
        exists for.  No proxycfg/xds lock is held here."""
        from consul_tpu import flight, telemetry
        group = GROUP_BY_URL.get(url, url)
        telemetry.incr_counter(("xds", "nacks"), 1,
                               labels={"type": group})
        flight.emit("xds.push.nack",
                    labels={"proxy": st.proxy_id or "", "type": group,
                            "detail": (detail or "")[:200]})

    @staticmethod
    def _note_pushed(st: _StreamState, url: str, n_rows: int,
                     mode: str = "full") -> None:
        """Per-type push counters, emitted as the response is handed
        to the gRPC machinery (no lock held).  `mode` distinguishes a
        per-subset delta from a whole snapshot on the wire (ISSUE 19
        accounting parity with the HTTP frontend)."""
        from consul_tpu import telemetry
        group = GROUP_BY_URL.get(url, url)
        telemetry.incr_counter(("xds", "pushes"), 1,
                               labels={"type": group, "mode": mode})
        if n_rows:
            telemetry.incr_counter(("xds", "resources"), float(n_rows),
                                   labels={"type": group,
                                           "mode": mode})

    def _push(self, st: _StreamState, urls: List[str],
              names_override: Optional[Dict[str, tuple]] = None):
        if st.watch is None:
            return
        snap = st.watch.fetch(0, timeout=0.0)
        if snap is None:
            return
        payload = self._payload(st, snap)
        pushed = False
        for url in urls:
            names = (names_override or {}).get(
                url, st.sent.get(url, (0, "", ()))[2])
            prev = st.sent.get(url)
            if names_override is None and prev is not None and \
                    prev[0] >= snap.version:
                continue    # this type already saw this version
            rows = _filter_names(payload.get(GROUP_BY_URL[url], []),
                                 names)
            nonce = st.next_nonce()
            st.sent[url] = (snap.version, nonce, names)
            self._note_pushed(st, url, len(rows))
            pushed = True
            yield xds_pb.build_response(url, rows, str(snap.version),
                                        nonce)
        if pushed:
            # runs after the LAST response was consumed by the stream
            # writer: stamps the per-proxy push clock and emits the
            # apply->push visibility stage once per snapshot
            st.watch.note_push(snap)
            from consul_tpu import flight
            flight.emit("xds.delta.pushed",
                        labels={"proxy": st.proxy_id or "",
                                "mode": "full",
                                "version": snap.version,
                                "index": snap.store_index})

    # ------------------------------------------------------------- delta

    def delta_aggregated_resources(self, request_iterator, context):
        st = _StreamState()
        # type_url -> {name: version_str} the client holds
        held: Dict[str, Dict[str, str]] = {}
        q: "queue.Queue" = queue.Queue()
        stop = threading.Event()
        threading.Thread(target=self._reader,
                         args=(request_iterator, q), daemon=True).start()
        watcher: Optional[threading.Thread] = None
        try:
            while True:
                kind, item = q.get()
                if kind == "eof":
                    return
                if kind == "req":
                    req = item
                    self._resolve(st, req.node, context)
                    if watcher is None:
                        watcher = threading.Thread(
                            target=self._watcher, args=(st, q, stop),
                            daemon=True)
                        watcher.start()
                    url = req.type_url
                    if url not in GROUP_BY_URL:
                        context.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            f"unknown type_url {url!r}")
                    if req.error_detail.code:
                        log.warning(
                            "xds delta NACK proxy=%s type=%s: %s",
                            st.proxy_id, url, req.error_detail.message)
                        self._note_nack(st, url,
                                        req.error_detail.message)
                        continue
                    have = held.setdefault(url, {})
                    for name, ver in req.initial_resource_versions.items():
                        have[name] = ver
                    if req.response_nonce and \
                            req.response_nonce == st.sent.get(
                                url, (0, "", ()))[1]:
                        continue        # ACK
                    st.sent.setdefault(url, (0, "", ()))
                    yield from self._push_delta(st, held, [url])
                elif kind == "update":
                    yield from self._push_delta(
                        st, held,
                        [u for u in URL_ORDER if u in st.sent])
        finally:
            stop.set()

    def _push_delta(self, st: _StreamState,
                    held: Dict[str, Dict[str, str]], urls: List[str]):
        if st.watch is None:
            return
        snap = st.watch.fetch(0, timeout=0.0)
        if snap is None:
            return
        payload = self._payload(st, snap)
        version = str(snap.version)
        pushed = False
        mode = "full"
        fell_back = False
        for url in urls:
            have = held.setdefault(url, {})
            rows = payload.get(GROUP_BY_URL[url], [])
            current = {xds_pb.resource_name(r): r for r in rows}
            # diff by CONTENT version, not snapshot counter: one
            # endpoint change must not resend every resource, and a
            # reconnecting client's initial_resource_versions (which
            # echo these hashes) suppress unchanged resources
            changed = [r for n, r in current.items()
                       if have.get(n) != xds_pb.resource_version(r)]
            removed = sorted(n for n in have if n not in current)
            if not changed and not removed:
                st.sent[url] = (snap.version, st.sent.get(
                    url, (0, "", ()))[1], ())
                continue
            # accounting (ISSUE 19): a diff against a non-empty held
            # set is a true per-subset delta; an empty held set means
            # this client is getting the whole type from scratch.  A
            # held set where EVERYTHING changed degenerated to a full
            # resend — a version-gap fallback in delta clothing.
            url_mode = "delta" if have else "full"
            if have and len(changed) == len(current) and current:
                fell_back = True
            for n, r in current.items():
                have[n] = xds_pb.resource_version(r)
            for n in removed:
                del have[n]
            nonce = st.next_nonce()
            st.sent[url] = (snap.version, nonce, ())
            self._note_pushed(st, url, len(changed), mode=url_mode)
            pushed = True
            if url_mode == "delta":
                mode = "delta"
            yield xds_pb.build_delta_response(
                url, changed, removed, version, nonce)
        if pushed:
            st.watch.note_push(snap)
            from consul_tpu import flight
            flight.emit("xds.delta.pushed",
                        labels={"proxy": st.proxy_id or "",
                                "mode": mode,
                                "version": snap.version,
                                "index": snap.store_index})
            if fell_back:
                flight.emit("xds.delta.fallback",
                            labels={"proxy": st.proxy_id or "",
                                    "from": 0,
                                    "version": snap.version})


SUBSCRIBE_SERVICE = "consultpu.stream.v1.StateChangeSubscription"


class SubscribeServicer:
    """gRPC snapshot-then-follow event streams (the reference's
    pbsubscribe Subscribe role, proto/pbsubscribe/subscribe.proto:14,
    agent/rpc/subscribe): a subscriber gets the materialized current
    state for its (topic, key), an end_of_snapshot marker, then live
    events; falling off the publisher buffer sends
    new_snapshot_to_follow and restarts the cycle.

    Frame contract: every data frame's payload is a JSON ARRAY — the
    full materialized row set for that frame's (topic, key) — in both
    the snapshot and live phases, so clients parse uniformly and a
    frame REPLACES their view of that key (empty array = gone)."""

    TOPICS = ("health", "services", "kv", "intentions", "nodes")

    def __init__(self, store,
                 authorize: Optional[Callable[[str, str, str], bool]]
                 = None):
        self.store = store
        self.authorize = authorize

    def _materialize(self, topic: str, key: str):
        """Current state of (topic, key) as typed per-entity frames:
        {entity_id: (frame_key, payload_field, message)}.  The
        subscribe loop DIFFS consecutive materializations, so live
        frames are per-entity deltas (pbsubscribe ServiceHealthUpdate
        role), never keyset re-dumps; key=\"\" = whole topic."""
        st = self.store
        out = {}
        if topic == "health":
            names = [key] if key else sorted(st.services())
            for n in names:
                for r in st.health_service_nodes(n):
                    s = r["service"]
                    inst = xds_pb.ServiceInstance(
                        node=s["node"], address=s["address"],
                        service_id=s["service_id"], service=n,
                        port=s["port"],
                        service_address=s["service_address"],
                        kind=s.get("kind") or "",
                        checks=[xds_pb.Check(
                            check_id=c["check_id"], name=c["name"],
                            status=c["status"],
                            service_id=c.get("service_id", ""),
                            output=c.get("output", ""),
                            node=c.get("node", ""))
                            for c in r["checks"]])
                    out[f"h|{n}|{s['node']}|{s['service_id']}"] = (
                        n, "service_health",
                        xds_pb.ServiceHealthUpdate(op="register",
                                                   instance=inst))
        elif topic == "services":
            for name, tags in sorted(st.services().items()):
                out[f"s|{name}"] = (name, "service_list",
                                    xds_pb.ServiceListUpdate(
                                        op="update", name=name,
                                        tags=list(tags)))
        elif topic == "kv":
            for e in st.kv_list(key):
                out[f"k|{e['key']}"] = (e["key"], "kv", xds_pb.KVUpdate(
                    op="update", key=e["key"], value=e["value"],
                    flags=e["flags"], modify_index=e["modify_index"],
                    session=e.get("session") or ""))
        elif topic == "intentions":
            for it in st.intention_list():
                out[f"i|{it['id']}"] = (it["id"], "intention",
                                        xds_pb.IntentionUpdate(
                    op="update", id=it["id"], source=it["source"],
                    destination=it["destination"], action=it["action"],
                    precedence=it["precedence"]))
        elif topic == "nodes":
            for r in st.nodes():
                if key and r["node"] != key:
                    continue
                out[f"n|{r['node']}"] = (r["node"], "node_update",
                                         xds_pb.NodeUpdate(
                    op="update", node=r["node"],
                    address=r["address"]))
        return out

    _DELETE_OP = {"service_health": "deregister"}

    def _diff_frames(self, topic, prev, cur, index):
        """Typed delta frames between two materializations: one frame
        per added/changed entity, one tombstone per removed entity."""
        frames = []
        for eid, (fkey, field, msg) in cur.items():
            old = prev.get(eid)
            if old is not None and \
                    old[2].SerializeToString(deterministic=True) == \
                    msg.SerializeToString(deterministic=True):
                continue
            frames.append(xds_pb.StreamEvent(
                index=index, topic=topic, key=fkey,
                op=getattr(msg, "op", "update") or "update",
                **{field: msg}))
        for eid, (fkey, field, msg) in prev.items():
            if eid in cur:
                continue
            tomb = type(msg)()
            tomb.CopyFrom(msg)
            tomb.op = self._DELETE_OP.get(field, "delete")
            frames.append(xds_pb.StreamEvent(
                index=index, topic=topic, key=fkey, op=tomb.op,
                **{field: tomb}))
        return frames

    def subscribe(self, request, context):
        from consul_tpu.stream.publisher import SnapshotRequired
        topic, key = request.topic, request.key
        if topic not in self.TOPICS:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"unsupported topic {topic!r} "
                          f"(want one of {', '.join(self.TOPICS)})")
        if self.authorize is not None:
            md = dict(context.invocation_metadata() or ())
            token = request.token or md.get("x-consul-token", "")
            if not self.authorize(token, topic, key):
                context.abort(grpc.StatusCode.PERMISSION_DENIED,
                              f"read denied on {topic}/{key}")
        pub = self.store.publisher
        resume_from = int(request.index) or None
        while context.is_active():
            # subscribe FIRST, snapshot second: no event between the
            # two can be missed (submatview discipline).  A resume
            # index replays history; since event frames carry no
            # payload history to diff against, ANY change past the
            # client's index makes its view unverifiable → reset with
            # new_snapshot_to_follow (the reference's stale-view
            # semantics, stream/subscription.go forceClose).
            view = {}
            if resume_from is not None:
                # seed the diff base BEFORE subscribing: an event
                # landing in the gap shows up in the replay check below
                view = self._materialize(topic, key)
            try:
                # kv keys are PREFIXES (like /v1/kv recurse), but the
                # publisher matches event keys exactly — follow the
                # whole topic and let the materialize/diff scope to the
                # prefix (an out-of-prefix write diffs to zero frames)
                sub_key = None if topic == "kv" else (key or None)
                sub = pub.subscribe(topic, sub_key,
                                    since_index=resume_from)
            except SnapshotRequired:
                resume_from = None
                continue
            stale_resume = False
            try:
                if resume_from is not None:
                    try:
                        pending = sub.events(timeout=0.0)
                        if topic == "kv" and key:
                            # whole-topic sub for a prefix watch:
                            # out-of-prefix writes don't stale THIS
                            # client's view
                            pending = [e for e in pending
                                       if e.key.startswith(key)]
                    except SnapshotRequired:
                        pending = [True]
                    if pending:
                        yield xds_pb.StreamEvent(
                            topic=topic, key=key,
                            new_snapshot_to_follow=True)
                        resume_from = None
                        stale_resume = True
                if not stale_resume and resume_from is None:
                    idx = self.store.index
                    view = self._materialize(topic, key)
                    for eid, (fkey, field, msg) in view.items():
                        yield xds_pb.StreamEvent(
                            index=idx, topic=topic, key=fkey,
                            op=getattr(msg, "op", "update"),
                            **{field: msg})
                    yield xds_pb.StreamEvent(
                        index=idx, topic=topic, key=key,
                        end_of_snapshot=True)
                while not stale_resume and context.is_active():
                    try:
                        batch = sub.events(timeout=1.0)
                    except SnapshotRequired:
                        yield xds_pb.StreamEvent(
                            topic=topic, key=key,
                            new_snapshot_to_follow=True)
                        resume_from = None
                        break
                    if not batch:
                        continue
                    # N raw events collapse into ONE diff against the
                    # last shipped view: each changed entity yields
                    # exactly one typed delta frame
                    idx = max(ev.index for ev in batch)
                    cur = self._materialize(topic, key)
                    for frame in self._diff_frames(topic, view, cur,
                                                   idx):
                        yield frame
                    view = cur
                else:
                    if stale_resume:
                        continue     # outer loop: fresh snapshot cycle
                    return           # client went away
            finally:
                sub.close()


class XdsGrpcServer:
    """The listening gRPC server; generic handlers bind the two ADS
    methods on their canonical paths so no generated service stubs are
    needed (grpc_tools isn't vendored — messages come from protoc, the
    service surface is two well-known stream-stream methods)."""

    def __init__(self, manager, host: str = "127.0.0.1", port: int = 0,
                 authorize: Optional[Callable[[str, str], bool]] = None,
                 subscribe_authorize: Optional[
                     Callable[[str, str, str], bool]] = None,
                 server_credentials=None, max_workers: int = 64):
        self.servicer = AdsServicer(manager, authorize=authorize)
        self.subscribe_servicer = SubscribeServicer(
            manager.store, authorize=subscribe_authorize)
        # Every ADS/Subscribe stream pins one worker thread for its
        # whole life (sync gRPC), so the pool bounds concurrent
        # streams.  maximum_concurrent_rpcs makes overflow fail FAST
        # with RESOURCE_EXHAUSTED instead of queueing behind parked
        # streams forever.
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            maximum_concurrent_rpcs=max_workers)
        handlers = {
            "StreamAggregatedResources": grpc.stream_stream_rpc_method_handler(
                self.servicer.stream_aggregated_resources,
                request_deserializer=xds_pb.DiscoveryRequest.FromString,
                response_serializer=xds_pb.DiscoveryResponse.SerializeToString),
            "DeltaAggregatedResources": grpc.stream_stream_rpc_method_handler(
                self.servicer.delta_aggregated_resources,
                request_deserializer=xds_pb.DeltaDiscoveryRequest.FromString,
                response_serializer=xds_pb.DeltaDiscoveryResponse.SerializeToString),
        }
        sub_handlers = {
            "Subscribe": grpc.unary_stream_rpc_method_handler(
                self.subscribe_servicer.subscribe,
                request_deserializer=xds_pb.SubscribeRequest.FromString,
                response_serializer=xds_pb.StreamEvent.SerializeToString),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),
             grpc.method_handlers_generic_handler(SUBSCRIBE_SERVICE,
                                                  sub_handlers)))
        addr = f"{host}:{port}"
        if server_credentials is not None:
            self.port = self._server.add_secure_port(
                addr, server_credentials)
        else:
            self.port = self._server.add_insecure_port(addr)
        self.host = host

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace).wait()
