"""Usage metrics: periodic state-store gauges.

The reference emits `consul.state.*` gauges (node/service/service-
instance/KV counts) from a usage-metrics reporter wired on every server
(agent/consul/usagemetrics/, server.go:568-587).  Same role here: a
UsageReporter samples the store on an interval and publishes gauges
through the telemetry registry, so /v1/agent/metrics and any statsd
sink see catalog growth without a store scan per request.
"""

from __future__ import annotations

import threading
from typing import Optional

from consul_tpu import telemetry


def snapshot_usage(store) -> dict:
    """One sample of the usage gauges (usagemetrics.go getUsage) — a
    single locked table pass (store.usage), never per-name scans."""
    return store.usage()


class UsageReporter:
    """Background sampler → telemetry gauges (usagemetrics.Run)."""

    def __init__(self, store, interval: float = 10.0,
                 registry: Optional[telemetry.Registry] = None):
        self.store = store
        self.interval = interval
        self.registry = registry or telemetry.default_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def emit_once(self) -> dict:
        usage = snapshot_usage(self.store)
        for key, val in usage.items():
            self.registry.set_gauge(("state", key), float(val))
        return usage

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.emit_once()
                except Exception:
                    pass   # a transient store error must not kill the loop

        self.emit_once()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
