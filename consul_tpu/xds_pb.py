"""Protobuf bridge for xDS resources.

Loads the generated envoy v3 modules (consul_tpu/xdsproto/gen, built by
tools/gen_xds_protos.sh) and converts between the JSON resource dicts
xds.py produces and real protobuf messages.  Because json_format uses
the descriptor pool the generated modules register, every nested
`typed_config` Any resolves to its concrete extension message — a
resource that fails from_dict is NOT valid Envoy v3, which makes this
module the validity oracle the golden tests lean on (the reference
pins go-control-plane protobuf types the same way,
agent/xds/golden_test.go).
"""

from __future__ import annotations

import os
import sys
from typing import List

_GEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "xdsproto", "gen")
if _GEN not in sys.path:
    sys.path.insert(0, _GEN)

from envoy.config.cluster.v3 import cluster_pb2            # noqa: E402
from envoy.config.endpoint.v3 import endpoint_pb2          # noqa: E402
from envoy.config.listener.v3 import listener_pb2          # noqa: E402
from envoy.config.route.v3 import route_pb2                # noqa: E402
from envoy.service.discovery.v3 import discovery_pb2       # noqa: E402
from google.protobuf import any_pb2, json_format           # noqa: E402

# also import every extension module so its descriptors land in the
# default pool for Any resolution
from envoy.config.rbac.v3 import rbac_pb2 as _rbac         # noqa: E402,F401
from envoy.extensions.filters.http.router.v3 import (      # noqa: E402,F401
    router_pb2 as _router)
from envoy.extensions.filters.listener.tls_inspector.v3 import (  # noqa: E402,F401
    tls_inspector_pb2 as _tlsi)
from envoy.extensions.filters.network.http_connection_manager.v3 import (  # noqa: E402,F401
    http_connection_manager_pb2 as _hcm)
from envoy.extensions.filters.network.rbac.v3 import (     # noqa: E402,F401
    rbac_pb2 as _net_rbac)
from envoy.extensions.filters.network.sni_cluster.v3 import (  # noqa: E402,F401
    sni_cluster_pb2 as _snic)
from envoy.extensions.filters.network.tcp_proxy.v3 import (  # noqa: E402,F401
    tcp_proxy_pb2 as _tcpp)
from envoy.extensions.transport_sockets.tls.v3 import (    # noqa: E402,F401
    tls_pb2 as _tls)

T = "type.googleapis.com/"

# top-level resource classes by canonical type URL
RESOURCE_TYPES = {
    T + "envoy.config.cluster.v3.Cluster": cluster_pb2.Cluster,
    T + "envoy.config.endpoint.v3.ClusterLoadAssignment":
        endpoint_pb2.ClusterLoadAssignment,
    T + "envoy.config.listener.v3.Listener": listener_pb2.Listener,
    T + "envoy.config.route.v3.RouteConfiguration":
        route_pb2.RouteConfiguration,
}

DiscoveryRequest = discovery_pb2.DiscoveryRequest
DiscoveryResponse = discovery_pb2.DiscoveryResponse
DeltaDiscoveryRequest = discovery_pb2.DeltaDiscoveryRequest
DeltaDiscoveryResponse = discovery_pb2.DeltaDiscoveryResponse

from consultpu.stream.v1 import subscribe_pb2 as _subscribe_pb2  # noqa: E402

SubscribeRequest = _subscribe_pb2.SubscribeRequest
StreamEvent = _subscribe_pb2.StreamEvent
Check = _subscribe_pb2.Check
ServiceInstance = _subscribe_pb2.ServiceInstance
ServiceHealthUpdate = _subscribe_pb2.ServiceHealthUpdate
ServiceListUpdate = _subscribe_pb2.ServiceListUpdate
KVUpdate = _subscribe_pb2.KVUpdate
IntentionUpdate = _subscribe_pb2.IntentionUpdate
NodeUpdate = _subscribe_pb2.NodeUpdate


def from_dict(resource: dict):
    """One xds.py resource dict (with its top-level "@type") → typed
    protobuf message.  Raises json_format.ParseError on any field the
    envoy v3 schema doesn't define — the validity check."""
    type_url = resource["@type"]
    cls = RESOURCE_TYPES[type_url]
    body = {k: v for k, v in resource.items() if k != "@type"}
    return json_format.ParseDict(body, cls())


def to_any(resource: dict) -> any_pb2.Any:
    msg = from_dict(resource)
    a = any_pb2.Any()
    a.Pack(msg)
    return a


def resource_name(resource: dict) -> str:
    return resource.get("name") or resource.get("cluster_name") or ""


def resource_version(resource: dict) -> str:
    """Stable per-resource content version for incremental xDS: delta
    pushes ship a resource only when THIS changes, and a reconnecting
    client's initial_resource_versions (which echo it) match again —
    the snapshot counter would force a full resend on every bump."""
    import hashlib
    import json as _json
    blob = _json.dumps(resource, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def build_response(type_url: str, resources: List[dict], version: str,
                   nonce: str) -> "discovery_pb2.DiscoveryResponse":
    """State-of-the-world DiscoveryResponse for one resource type."""
    resp = discovery_pb2.DiscoveryResponse(
        version_info=version, type_url=type_url, nonce=nonce)
    resp.control_plane.identifier = "consul_tpu"
    for r in resources:
        resp.resources.add().Pack(from_dict(r))
    return resp


def build_delta_response(type_url: str, changed: List[dict],
                         removed: List[str], version: str,
                         nonce: str) -> "discovery_pb2.DeltaDiscoveryResponse":
    resp = discovery_pb2.DeltaDiscoveryResponse(
        system_version_info=version, type_url=type_url, nonce=nonce,
        removed_resources=removed)
    for r in changed:
        res = resp.resources.add()
        res.name = resource_name(r)
        res.version = resource_version(r)
        res.resource.Pack(from_dict(r))
    return resp
