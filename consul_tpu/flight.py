"""Flight recorder: a process-wide structured event journal.

PR 1's metrics and PR 2's profiles record *what is slow*; this module
records *what happened when*.  The reference scatters that story over
streaming log monitors (logging/monitor), `consul debug` archives, and
Serf user events — an operator reconstructing an incident greps three
surfaces and correlates timestamps by hand.  Here every layer that
already KNOWS something happened (raft elections, WAL recovery,
membership flaps, chaos injections, autopilot removals, user events)
journals one structured row into a single bounded ring:

    {"seq", "ts", "name", "severity", "labels", "trace_id", "msg"}

Design constraints, deliberate:

  * **Registered schema.**  Every event name and its allowed label
    keys are declared in `CATALOG` below — a literal dict, so the
    `event-names` lint checker (tools/lint/checkers/metric_names.py)
    can validate emit sites statically, and `emit()` enforces the same
    contract at runtime.  An unregistered name is a bug, not a row.
  * **Bounded memory, bounded emission cost.**  A deque ring (one
    lock, one append) exactly like trace.py's span ring; label values
    are clamped; nothing on the emit path blocks.  Optional WAL spill
    writes evicted/all rows through the `storage.py` seam (so the
    storage nemesis can sit under it), best-effort, never fsynced on
    the emit path.
  * **Deterministic under the nemesis.**  `ts` comes from the
    caller's clock when passed explicitly (raft passes its virtual
    `now`; the SWIM harness passes the device tick) and from the
    recorder's `clock` otherwise — chaos scenarios install a scoped
    recorder with a constant clock, so the journal of a seeded run is
    byte-identical across replays (chaos_soak --check asserts it).
  * **O(flaps), never O(N).**  The membership emitter consumes
    `oracle.members_delta()` — the PR 6 gather-free incremental read —
    so a 16M-node pool with 50 flaps per checkpoint journals 50 rows
    and moves 50 rows over the device→host seam (asserted by spying
    `oracle._to_host`).

Serving: /v1/agent/events (blocking-query + ?since= cursor), events
multiplexed onto /v1/agent/monitor streams through the log buffer, and
`debug.capture()` bundles carry the ring as events.jsonl.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from consul_tpu import locks

SEVERITIES = ("info", "warn", "error")

RING = 4096
MAX_LABELS = 8
MAX_LABEL_VALUE = 128

# ---------------------------------------------------------------------------
# The event catalog: name -> {"severity": default, "labels": allowed keys}.
#
# A LITERAL dict, deliberately: the event-names lint checker parses this
# assignment's AST to validate emit sites without importing anything.
# Register new events here (and nowhere else); an emit of an
# unregistered name raises at runtime and fails the lint gate at review
# time.  Label keys are the bounded vocabulary — values vary (node ids,
# terms), keys may not.
# ---------------------------------------------------------------------------

CATALOG: Dict[str, dict] = {
    # agent lifecycle
    "agent.started": {"severity": "info", "labels": ("node",)},
    "agent.stopped": {"severity": "info", "labels": ("node",)},
    # raft / consensus (emitters in consensus/raft.py, staged through
    # the same buffer as the raft metrics so nothing emits under the
    # raft lock; ts is the raft tick's `now` — virtual under the
    # nemesis, wall-clock live)
    "raft.election.started": {"severity": "info",
                              "labels": ("node", "term")},
    "raft.election.won": {"severity": "info", "labels": ("node", "term")},
    "raft.leadership.lost": {"severity": "warn",
                             "labels": ("node", "term")},
    "raft.term.changed": {"severity": "info",
                          "labels": ("node", "term", "from")},
    "raft.snapshot.installed": {"severity": "info",
                                "labels": ("node", "index", "term")},
    "raft.snapshot.restored": {"severity": "info",
                               "labels": ("node", "index", "term")},
    "raft.recovery.completed": {
        "severity": "info",
        "labels": ("node", "torn_tail", "corrupt_frame", "meta_fallback",
                   "snap_fallback", "snap_lost", "wal_window_dropped")},
    # membership (the oracle's members_delta flap feed + the chaos
    # harness's ground-truth commit diffs)
    "serf.member.flap": {"severity": "info",
                         "labels": ("node", "status", "tick")},
    "serf.flap.truncated": {"severity": "warn",
                            "labels": ("count", "limit", "tick")},
    # serf user events (oracle.fire_event; trace id rides from the
    # HTTP entry contextvar so /v1/event/fire correlates end to end)
    "serf.user_event": {"severity": "info",
                        "labels": ("name", "origin", "id", "ltime")},
    # chaos nemesis: every injected fault is a correlated row so a
    # soak violation prints a timeline next to the seed reproducer
    "chaos.fault.injected": {"severity": "warn",
                             "labels": ("fault", "target", "tick")},
    "chaos.fault.healed": {"severity": "info",
                           "labels": ("fault", "target", "tick")},
    # autopilot (server-health transitions + dead-server cleanup)
    "autopilot.health.changed": {"severity": "warn",
                                 "labels": ("server", "healthy")},
    "autopilot.server.removed": {"severity": "warn",
                                 "labels": ("server",)},
    # runtime (the tick profiler's recompile watchdog)
    "runtime.recompile": {"severity": "warn",
                          "labels": ("fn", "cache_size")},
    # commit-to-visibility pipeline (consul_tpu/visibility.py): a
    # watch-delivery stage lagging its raft apply past the stall budget
    # (dc: the datacenter dimension of the federated view, ISSUE 15)
    "kv.visibility.stall": {"severity": "warn",
                            "labels": ("stage", "index", "ms", "dc")},
    # WAN federation data plane (consul_tpu/wanfed.py, dc-labeled
    # gateways only — the chaos LinkProxy interposer stays silent so a
    # seeded scenario's journal remains byte-identical): one row per
    # accepted cross-DC splice, stamped with the trace id sniffed from
    # the spliced request's X-Consul-Trace-Id header so the gateway
    # hop joins the writer's commit-to-visibility trace; failed = the
    # upstream dial was refused (the fail-fast the live_gateway_loss
    # scenario audits)
    "wanfed.splice.opened": {"severity": "info",
                             "labels": ("gateway", "dc")},
    "wanfed.splice.failed": {"severity": "warn",
                             "labels": ("gateway", "dc", "error")},
    # stream plane (stream/publisher.py): a subscriber draining a queue
    # that backed up past the slow threshold, and a follower that fell
    # off the topic buffer tail (forced re-snapshot)
    "stream.subscriber.slow": {"severity": "warn",
                               "labels": ("topic", "depth")},
    "stream.subscriber.reset": {"severity": "warn",
                                "labels": ("topic", "key")},
    # read plane (consul_tpu/readplane.py): a read this node REFUSED —
    # ?max_stale bound exceeded by the replica's own lag, default-mode
    # read with no cluster leader, conflicting modes, or a stale
    # leader-forward hint bouncing off a non-leader.  The chaos
    # timeline's proof that lag-bounded rejects fire when they must.
    "readplane.rejected": {"severity": "warn",
                           "labels": ("reason", "route", "node")},
    # overload defense plane (consul_tpu/ratelimit.py): an ingress
    # request shed by the token-bucket limiter, and a leader apply
    # NACKed before the raft append (queue_full / deadline — a
    # definite non-write, never an ambiguous timeout).  Both emitters
    # throttle to one row per second per class so a rejection storm
    # cannot wash the ring of the fault that caused it.
    "ratelimit.rejected": {"severity": "warn",
                           "labels": ("route_class", "mode")},
    "raft.apply.rejected": {"severity": "warn",
                            "labels": ("reason", "pending")},
    # self-sizing limits (consul_tpu/ratelimit.py DynamicLimitController,
    # ISSUE 18): every AIMD walk of the write_rate journals one row —
    # direction is `decrease` (multiplicative backoff on an overloaded
    # apply EMA / visibility p99) or `increase` (additive probe after
    # the hysteresis streak of healthy ticks)
    "ratelimit.adjusted": {"severity": "info",
                           "labels": ("direction", "rate", "reason")},
    # cross-DC replication divergence TRANSITIONS (acl/replication.py,
    # ISSUE 18): one row when a replicator can no longer prove sync
    # with the primary (content-hash mismatch or unreachable primary
    # under a WAN partition), one when a clean round converges it
    # back — transitions, not rounds, so a long partition is one row
    "replication.diverged": {"severity": "warn",
                             "labels": ("type", "source_dc")},
    "replication.converged": {"severity": "info",
                              "labels": ("type", "source_dc")},
    # stream plane: a subscriber whose bounded buffer filled without a
    # drain (sustained lag) was EVICTED — its consumer gets a
    # SnapshotRequired reset; `count` aggregates evictions staged in
    # one publish/flush cycle so 10k simultaneous evictions journal a
    # handful of rows, not 10k
    "stream.subscriber.evicted": {"severity": "warn",
                                  "labels": ("topic", "count",
                                             "depth")},
    # mesh control plane (consul_tpu/proxycfg.py / xds_grpc.py,
    # ISSUE 16): a proxy snapshot rebuild (staged off the proxycfg
    # condition, trace id inherited from the triggering stream Event),
    # an ADS NACK (the client REJECTED a pushed config — the xds
    # server logs-and-waits, so the journal is where the rejection
    # becomes visible), and a rebuild/push stage lagging its raft
    # apply past the stall budget (the xds twin of
    # kv.visibility.stall)
    "xds.rebuild": {"severity": "info",
                    "labels": ("proxy", "kind", "version", "index")},
    "xds.push.nack": {"severity": "warn",
                      "labels": ("proxy", "type", "detail")},
    "xds.visibility.stall": {"severity": "warn",
                             "labels": ("stage", "index", "ms",
                                        "proxy_kind")},
    # delta-xDS plane (ISSUE 19): one row per ADS response that
    # shipped config — mode=delta|full tells whether the client got a
    # versioned per-subset diff or a whole snapshot, index is the
    # triggering store apply (correlates push back to the commit for
    # the stale-route checker); a fallback row whenever a delta-mode
    # client hit a version gap and was downgraded to a full snapshot;
    # and a stale-route row per invariant violation the churn-storm
    # checker found (a proxy held a config routing to a deregistered
    # instance past the SLO — ms is how far past)
    "xds.delta.pushed": {"severity": "info",
                         "labels": ("proxy", "mode", "version",
                                    "index")},
    "xds.delta.fallback": {"severity": "info",
                           "labels": ("proxy", "from", "version")},
    "xds.stale_route": {"severity": "error",
                        "labels": ("proxy", "service", "ms")},
    # lock-discipline plane (consul_tpu/locks.py, audit mode): an
    # acquisition that waited past the contention threshold, a hold
    # past the hold budget, and an observed acquisition-order cycle —
    # the runtime twins of the lock-order/guarded-by lint checkers.
    # Journaled to the DEFAULT recorder only (never a chaos scenario's
    # scoped deterministic ring) and always after the audited lock is
    # released.
    "runtime.lock.contention": {"severity": "warn",
                                "labels": ("lock", "ms")},
    "runtime.lock.held_too_long": {"severity": "warn",
                                   "labels": ("lock", "ms")},
    "runtime.lock.cycle": {"severity": "error", "labels": ("edge",)},
}


class FlightRecorder:
    """Bounded event ring + optional WAL spill + subscriber wakeups."""

    def __init__(self, ring: int = RING,
                 clock: Callable[[], float] = time.time,
                 forward_to_log: bool = True):
        self._ring: deque = deque(maxlen=ring)  # guarded-by: _lock
        self._clock = clock
        self._forward_to_log = forward_to_log
        self._lock = locks.make_lock("flight.ring")
        self._cond = locks.make_condition(self._lock)
        self._seq = 0               # guarded-by: _lock
        self._spill = None          # guarded-by: _lock — (ops, fh, path)
        self._spill_lock = locks.make_lock("flight.spill")
        # re-entrancy guard: a nemesis-backed spill (FaultyStorage)
        # journals its OWN fault events from inside ops.write() — that
        # nested emit must skip the spill (ring-only) or it would
        # deadlock on the spill lock / recurse through the fault
        self._spill_tls = threading.local()
        # emit-path re-entrancy guard (the PR 9 SIGUSR1 hazard): set
        # for the duration of any critical section OR a full emit, so
        # an emit re-entered on the same thread (a signal handler
        # interrupting mid-emit, or an emit-observer on the log fan-out
        # emitting back into the ring) takes the non-blocking ring-only
        # path instead of self-deadlocking on the non-reentrant lock or
        # recursing through the fan-out
        self._emit_tls = threading.local()
        self.dropped = 0            # spill write failures (best-effort)
        self.reentrant_dropped = 0  # re-entrant emits the ring was too
        #                             busy to take (never a deadlock)
        locks.register_guards(self, self._lock,
                              "_ring", "_seq", "_spill")

    # ----------------------------------------------------------------- emit

    @contextmanager
    def _ring_lock(self):
        """`with self._lock` plus the re-entrancy flag: any same-thread
        emit() that starts while we are inside (a signal handler, an
        emit-observer) sees `busy` and takes the non-blocking path."""
        tls = self._emit_tls
        prev = getattr(tls, "busy", False)
        tls.busy = True
        try:
            with self._lock:
                yield
        finally:
            tls.busy = prev

    def emit(self, name: str, labels: Optional[dict] = None,
             severity: Optional[str] = None, msg: str = "",
             trace_id: Optional[str] = None,
             ts: Optional[float] = None) -> int:
        """Journal one event; returns its seq.  Raises ValueError on an
        unregistered name or undeclared label key — the runtime twin of
        the event-names lint gate (all emitters are in-repo; misuse is
        a bug to surface, not traffic to shed).

        Re-entrancy safe: an emit re-entered on the SAME thread (a
        signal handler firing mid-emit — the hazard PR 9's SIGUSR1
        handler worked around with a flag-only dance — or a log-plane
        observer emitting from inside the fan-out) journals ring-only
        via a non-blocking acquire, or drops with `reentrant_dropped`
        incremented when the ring lock is provably held by this very
        thread.  It never deadlocks and never recurses the fan-out;
        returns -1 for a dropped re-entrant row."""
        schema = CATALOG.get(name)
        if schema is None:
            raise ValueError(f"unregistered event name {name!r} — "
                             f"add it to flight.CATALOG")
        allowed = schema.get("labels", ())
        lbl: Dict[str, str] = {}
        if labels:
            if len(labels) > MAX_LABELS:
                raise ValueError(f"{len(labels)} labels on {name!r} > "
                                 f"{MAX_LABELS}")
            for k, v in labels.items():
                if k not in allowed:
                    raise ValueError(
                        f"label {k!r} not declared for event {name!r} "
                        f"(allowed: {allowed})")
                lbl[k] = str(v)[:MAX_LABEL_VALUE]
        sev = severity or schema.get("severity", "info")
        if sev not in SEVERITIES:
            raise ValueError(f"severity {sev!r} not one of {SEVERITIES}")
        if trace_id is None:
            from consul_tpu import trace
            trace_id = trace.current_trace() or ""
        rec = {"seq": 0,        # assigned under the lock below
               "ts": round(self._clock() if ts is None else ts, 6),
               "name": name, "severity": sev, "labels": lbl,
               "trace_id": trace_id}
        if msg:
            rec["msg"] = msg
        tls = self._emit_tls
        if getattr(tls, "busy", False):
            # re-entered on this thread: best-effort ring-only append —
            # no spill, no log fan-out, no blocking on a lock the
            # interrupted frame below us may already hold
            if self._lock.acquire(False):
                try:
                    # lint: ok=guarded-by (held via the explicit non-blocking acquire above)
                    self._seq += 1
                    # lint: ok=guarded-by (held via the explicit non-blocking acquire above)
                    rec["seq"] = self._seq
                    # lint: ok=guarded-by (held via the explicit non-blocking acquire above)
                    self._ring.append(rec)
                    self._cond.notify_all()
                finally:
                    self._lock.release()
                return rec["seq"]
            self.reentrant_dropped += 1
            return -1
        tls.busy = True
        try:
            with self._ring_lock():
                self._seq += 1
                rec["seq"] = self._seq
                self._ring.append(rec)
                spill = self._spill
                self._cond.notify_all()
            if spill is not None and \
                    not getattr(self._spill_tls, "busy", False):
                # spill I/O OUTSIDE the ring lock: a slow disk must
                # never serialize emitters/readers/waiters behind a
                # write (the whole point of raft's staged emission).
                # The dedicated spill lock keeps lines whole;
                # concurrent emitters may interleave out of seq order —
                # rows carry their seq.  Events emitted FROM the spill
                # write itself (a nemesis disk journaling its injected
                # fault) stay ring-only.
                ops, f, _ = spill
                self._spill_tls.busy = True
                try:
                    with self._spill_lock:
                        # re-check under the spill lock: a concurrent
                        # detach_spill() may have popped + closed the
                        # handle since we snapshotted it above.  A
                        # benign unlocked READ by design: both outcomes
                        # of the race are safe (stale non-None writes a
                        # line the detach already drained behind the
                        # spill lock; stale None drops one spill row).
                        # lint: ok=guarded-by (benign racy re-check; both outcomes safe under _spill_lock)
                        if self._spill is spill:
                            ops.write(
                                f, (json.dumps(rec, sort_keys=True)
                                    + "\n").encode())
                except (OSError, ValueError):
                    self.dropped += 1       # spill is best-effort
                finally:
                    self._spill_tls.busy = False
            if self._forward_to_log:
                self._to_log(rec)
        finally:
            tls.busy = False
        return rec["seq"]

    @staticmethod
    def _to_log(rec: dict) -> None:
        """Multiplex the event onto the log plane: one formatted line
        into the process LogBuffer, which fans it out to every live
        /v1/agent/monitor subscription (logging/monitor role)."""
        from consul_tpu.logging import default_buffer
        level = {"info": "INFO", "warn": "WARN",
                 "error": "ERROR"}[rec["severity"]]
        kv = "".join(f" {k}={v}" for k, v in rec["labels"].items())
        wall = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        default_buffer().write(
            f"{wall} [{level}] flight: event={rec['name']}"
            f" seq={rec['seq']}{kv}"
            + (f" trace_id={rec['trace_id']}" if rec["trace_id"] else ""))

    # ----------------------------------------------------------------- read

    @property
    def last_seq(self) -> int:
        with self._ring_lock():
            return self._seq

    def read_page(self, since: int = 0, limit: Optional[int] = None,
                  name: Optional[str] = None,
                  severity: Optional[str] = None
                  ) -> Tuple[List[dict], int]:
        """(rows, horizon): events with seq > `since`, oldest first,
        optionally filtered and capped to the OLDEST `limit` rows —
        forward-paging semantics (`tail()` serves the newest-N case).
        `horizon` is the journal's last seq captured under the SAME
        lock as the scan: when rows is empty, every event ≤ horizon
        was examined and did not match, so a cursor may safely advance
        to it (the blocking-query endpoint leans on this — echoing a
        stale cursor past live non-matching traffic would busy-loop
        the client).  `limit=0` examines nothing, so its horizon is
        `since` itself — never an advance past rows the zero-size page
        merely truncated away."""
        if limit == 0:
            return [], since
        with self._ring_lock():
            out = [dict(r) for r in self._ring if r["seq"] > since]
            horizon = self._seq
        if name is not None:
            out = [r for r in out if r["name"] == name]
        if severity is not None:
            out = [r for r in out if r["severity"] == severity]
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out, horizon

    def read(self, since: int = 0, limit: Optional[int] = None,
             name: Optional[str] = None,
             severity: Optional[str] = None) -> List[dict]:
        """read_page() without the horizon."""
        return self.read_page(since, limit, name, severity)[0]

    def tail(self, n: int) -> List[dict]:
        with self._ring_lock():
            out = list(self._ring)[-n:] if n else []
        return [dict(r) for r in out]

    def wait(self, since: int, timeout: float) -> int:
        """Block until an event with seq > `since` exists (or timeout);
        returns the latest seq — the blocking-query wait behind
        /v1/agent/events?since=N&wait=T."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._ring_lock():
            while self._seq <= since:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._seq

    def dump_jsonl(self) -> bytes:
        """The whole ring as JSON lines (the debug-archive section;
        sort_keys so a fixed-clock recorder's dump is byte-stable)."""
        with self._ring_lock():
            rows = list(self._ring)
        return "".join(json.dumps(r, sort_keys=True) + "\n"
                       for r in rows).encode()

    def clear(self) -> None:
        with self._ring_lock():
            self._ring.clear()

    # ---------------------------------------------------------------- spill

    def attach_spill(self, path: str, ops=None) -> None:
        """Append every subsequent event as a JSON line to `path`
        through the storage seam (`storage.StorageOps`) — the WAL
        spill: the ring bounds memory, the spill keeps history.  Never
        fsynced on the emit path; `detach_spill()` flushes."""
        from consul_tpu import storage
        io = ops or storage.OS
        f = io.open_append(path)
        with self._ring_lock():
            self._spill = (io, f, path)

    def detach_spill(self, sync: bool = False) -> None:
        with self._ring_lock():
            spill, self._spill = self._spill, None
        if spill is None:
            return
        ops, f, _ = spill
        try:
            with self._spill_lock:      # drain in-flight line writes
                if sync:
                    ops.fsync(f)
                f.close()
        except OSError:
            self.dropped += 1


# ---------------------------------------------------------------------------
# process-wide default + scoped override (the chaos harness installs a
# deterministic-clock recorder for the duration of one scenario)
# ---------------------------------------------------------------------------

_default = FlightRecorder()
_current = _default
_swap_lock = threading.Lock()


def default_recorder() -> FlightRecorder:
    return _default


def current() -> FlightRecorder:
    return _current


@contextmanager
def use(recorder: FlightRecorder):
    """Route module-level `emit()` to `recorder` within the block.
    Process-global (not thread-local) by design: the nemesis owns the
    process while a scenario runs, and emitters deep in raft/oracle
    must not need a recorder threaded through every signature."""
    global _current
    with _swap_lock:
        prev, _current = _current, recorder
    try:
        yield recorder
    finally:
        with _swap_lock:
            _current = prev


def emit(name: str, labels: Optional[dict] = None,
         severity: Optional[str] = None, msg: str = "",
         trace_id: Optional[str] = None,
         ts: Optional[float] = None) -> int:
    return _current.emit(name, labels=labels, severity=severity,
                         msg=msg, trace_id=trace_id, ts=ts)
