"""Auto-config client: JWT-authorized bootstrap of a fresh agent.

The reference's auto-config flow (agent/auto-config/auto_config.go
InitialConfiguration; server side auto_config_endpoint.go): a new
client agent knows only (a) a server address and (b) an *intro token*
(a JWT from its platform, e.g. a Kubernetes service account).  It calls
AutoConfig.InitialConfiguration over the server's insecure bootstrap
port; the server validates the JWT against a configured auth method,
mints an ACL token through binding rules, and returns runtime-config
fields plus TLS material.  The client persists the response
(agent/auto-config/persist.go) and applies it on every later start.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

PERSIST_FILE = "auto-config.json"


def initial_configuration(addr: Tuple[str, int], jwt: str,
                          node_name: str = "agent",
                          ssl_context=None,
                          server_hostname: Optional[str] = None,
                          data_dir: Optional[str] = None,
                          timeout: float = 10.0) -> dict:
    """Fetch (and optionally persist) the pushed configuration.

    `addr` is the server's bootstrap (or main RPC) address;
    `ssl_context` the anonymous client context for the bootstrap
    listener (tlsutil.anonymous_context) or None for plaintext RPC."""
    from consul_tpu.rpc import RpcClient
    client = RpcClient(ssl_context=ssl_context,
                       server_hostname=server_hostname, timeout=timeout)
    try:
        out = client.call(addr, "auto_config",
                          {"jwt": jwt, "node_name": node_name})
    finally:
        client.close()   # one-shot bootstrap: don't leak the pool
    if data_dir:
        persist(data_dir, out)
    return out


def persist(data_dir: str, response: dict) -> None:
    """Atomic write of the bootstrap response (persist.go)."""
    os.makedirs(data_dir, exist_ok=True)
    from consul_tpu import storage
    storage.atomic_replace(os.path.join(data_dir, PERSIST_FILE),
                           json.dumps(response).encode())


def load_persisted(data_dir: str) -> Optional[dict]:
    """Previously persisted bootstrap response, or None (corrupt or
    missing files must not block startup — the caller re-bootstraps)."""
    try:
        with open(os.path.join(data_dir, PERSIST_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def bootstrap_or_load(addr, jwt: str, data_dir: str,
                      node_name: str = "agent", ssl_context=None,
                      server_hostname: Optional[str] = None) -> dict:
    """Start-up entry: reuse the persisted config when present, else
    perform the initial RPC and persist (auto_config.go
    readPersistedAutoConfig → InitialConfiguration fallback)."""
    cached = load_persisted(data_dir)
    if cached is not None:
        return cached
    return initial_configuration(addr, jwt, node_name=node_name,
                                 ssl_context=ssl_context,
                                 server_hostname=server_hostname,
                                 data_dir=data_dir)
