"""Telemetry: counters/gauges/samples with sink fan-out.

The reference initializes armon/go-metrics with statsite/statsd/
dogstatsd/prometheus/circonus sinks (lib/telemetry.go:21 TelemetryConfig,
InitTelemetry) and instruments every subsystem (rpc.go:815, leader.go:196
…), surfacing an in-memory aggregate at /v1/agent/metrics.  Same shape
here: a process-wide Registry with incr_counter / set_gauge / add_sample,
an in-memory aggregating sink serving the metrics endpoint, and an
optional statsd UDP line sink.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple


class _Sample:
    __slots__ = ("count", "total", "min", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)


class StatsdSink:
    """Plain statsd line protocol over UDP (lib/telemetry.go statsd_addr)."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def emit(self, kind: str, name: str, value: float) -> None:
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        try:
            self.sock.sendto(f"{name}:{value}|{suffix}".encode(), self.addr)
        except OSError:
            pass


class DogstatsdSink(StatsdSink):
    """Datadog's statsd dialect: the same line protocol plus |#tags
    (lib/telemetry.go dogstatsd_addr / dogstatsd_tags)."""

    def __init__(self, addr: str, tags: Optional[List[str]] = None):
        super().__init__(addr)
        self._suffix = ("|#" + ",".join(tags)) if tags else ""

    def emit(self, kind: str, name: str, value: float) -> None:
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        try:
            self.sock.sendto(
                f"{name}:{value}|{suffix}{self._suffix}".encode(),
                self.addr)
        except OSError:
            pass


class StatsiteSink:
    """statsite speaks the statsd line protocol over TCP
    (lib/telemetry.go statsite_addr).  Lines flush through a bounded
    queue + background writer so metric EMISSION never blocks the hot
    path on an unreachable collector (go-metrics' statsite sink
    buffers through a channel the same way); overflow drops lines."""

    _QUEUE_CAP = 4096

    def __init__(self, addr: str):
        import queue as _queue
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self._q: "_queue.Queue[bytes]" = _queue.Queue(self._QUEUE_CAP)
        self._sock: Optional[socket.socket] = None
        threading.Thread(target=self._flush_loop, daemon=True).start()

    def emit(self, kind: str, name: str, value: float) -> None:
        import queue as _queue
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        try:
            self._q.put_nowait(f"{name}:{value}|{suffix}\n".encode())
        except _queue.Full:
            pass                      # collector down: shed, don't stall

    def _flush_loop(self) -> None:
        import time as _time
        while True:
            line = self._q.get()
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(self.addr,
                                                          timeout=1.0)
                self._sock.sendall(line)
            except OSError:
                try:
                    if self._sock is not None:
                        self._sock.close()
                finally:
                    self._sock = None
                _time.sleep(0.5)      # backoff before the next dial


class Registry:
    def __init__(self, prefix: str = "consul"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._samples: Dict[str, _Sample] = {}
        self._sinks: List[StatsdSink] = []

    def add_statsd_sink(self, addr: str) -> None:
        self._sinks.append(StatsdSink(addr))

    def add_dogstatsd_sink(self, addr: str,
                           tags: Optional[List[str]] = None) -> None:
        self._sinks.append(DogstatsdSink(addr, tags))

    def add_statsite_sink(self, addr: str) -> None:
        self._sinks.append(StatsiteSink(addr))

    def _name(self, parts) -> str:
        if isinstance(parts, str):
            return f"{self.prefix}.{parts}"
        return ".".join([self.prefix, *parts])

    def incr_counter(self, name, value: float = 1.0) -> None:
        n = self._name(name)
        with self._lock:
            self._counters[n] += value
        for s in self._sinks:
            s.emit("counter", n, value)

    def set_gauge(self, name, value: float) -> None:
        n = self._name(name)
        with self._lock:
            self._gauges[n] = value
        for s in self._sinks:
            s.emit("gauge", n, value)

    def add_sample(self, name, value: float) -> None:
        n = self._name(name)
        with self._lock:
            self._samples.setdefault(n, _Sample()).add(value)
        for s in self._sinks:
            s.emit("sample", n, value * 1000.0)

    def measure_since(self, name, t0: float) -> None:
        self.add_sample(name, time.perf_counter() - t0)

    # ---------------------------------------------------------------- dump

    def dump(self) -> dict:
        """/v1/agent/metrics shape (agent/agent_endpoint.go
        AgentMetrics)."""
        with self._lock:
            return {
                "Timestamp": time.strftime("%Y-%m-%d %H:%M:%S +0000",
                                           time.gmtime()),
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Counters": [{"Name": k, "Count": v}
                             for k, v in sorted(self._counters.items())],
                "Samples": [{"Name": k, "Count": s.count,
                             "Sum": round(s.total, 6),
                             "Min": round(s.min, 6),
                             "Max": round(s.max, 6),
                             "Mean": round(s.total / s.count, 6)
                             if s.count else 0.0}
                            for k, s in sorted(self._samples.items())],
            }


    def prometheus(self) -> str:
        """Prometheus text exposition (the PrometheusOpts role,
        lib/telemetry.go:200; served at /v1/agent/metrics
        ?format=prometheus like the reference's
        agent_endpoint.go AgentMetrics prometheus handler).

        Names sanitize '.'/'-' to '_'; counters map to `counter`,
        gauges to `gauge`, and samples expose the go-metrics summary
        shape as _count/_sum plus min/max gauges (quantile streams
        aren't tracked; min/max is what the in-memory sink has)."""

        def san(n: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_"
                           for c in n)

        with self._lock:
            out = []
            for k, v in sorted(self._counters.items()):
                n = san(k)
                out.append(f"# TYPE {n} counter")
                out.append(f"{n} {v:g}")
            for k, v in sorted(self._gauges.items()):
                n = san(k)
                out.append(f"# TYPE {n} gauge")
                out.append(f"{n} {v:g}")
            for k, s in sorted(self._samples.items()):
                n = san(k)
                out.append(f"# TYPE {n} summary")
                out.append(f"{n}_sum {s.total:g}")
                out.append(f"{n}_count {s.count}")
                if s.count:
                    out.append(f"# TYPE {n}_min gauge")
                    out.append(f"{n}_min {s.min:g}")
                    out.append(f"# TYPE {n}_max gauge")
                    out.append(f"{n}_max {s.max:g}")
            return "\n".join(out) + "\n"


# process-wide default registry (go-metrics global pattern)
_default = Registry()


def default_registry() -> Registry:
    return _default


def incr_counter(name, value: float = 1.0) -> None:
    _default.incr_counter(name, value)


def set_gauge(name, value: float) -> None:
    _default.set_gauge(name, value)


def add_sample(name, value: float) -> None:
    _default.add_sample(name, value)


def measure_since(name, t0: float) -> None:
    _default.measure_since(name, t0)
