"""Telemetry: counters/gauges/samples with labels, quantiles, sink fan-out.

The reference initializes armon/go-metrics with statsite/statsd/
dogstatsd/prometheus/circonus sinks (lib/telemetry.go:21 TelemetryConfig,
InitTelemetry) and instruments every subsystem (rpc.go:815, leader.go:196
…), surfacing an in-memory aggregate at /v1/agent/metrics.  Same shape
here: a process-wide Registry with incr_counter / set_gauge / add_sample
(each taking optional go-metrics-style labels), an in-memory aggregating
sink serving the metrics endpoint, and optional statsd-family line sinks.

Samples carry streaming P50/P90/P99 via a fixed-size reservoir (the
go-metrics AggregateSample + prometheus summary role): bounded memory
per metric, quantiles computed only at dump/scrape time — nothing on the
emission hot path beyond one reservoir slot write.
"""

from __future__ import annotations

import random
import socket
import threading
import time
import zlib
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

# label normal form: sorted tuple of (key, value) string pairs — hashable,
# deterministic, order-insensitive (go-metrics Label slices, order-free)
LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels) -> LabelKey:
    if not labels:
        return ()
    if isinstance(labels, dict):
        items = labels.items()
    else:
        items = labels
    return tuple(sorted((str(k), str(v)) for k, v in items))


class _Sample:
    """Aggregate + fixed-size reservoir (Vitter's algorithm R).

    The reservoir is the "small fixed-size estimator" behind the
    P50/P90/P99 summaries: uniform over the whole stream, RESERVOIR
    floats of memory regardless of count.  Seeded RNG per instance so
    dumps are reproducible run-to-run."""

    __slots__ = ("count", "total", "min", "max", "_res", "_rng")

    RESERVOIR = 256

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._res: List[float] = []
        self._rng = random.Random(0x5EED)

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._res) < self.RESERVOIR:
            self._res.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.RESERVOIR:
                self._res[j] = v

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> List[float]:
        """Nearest-rank quantiles over the reservoir (exact while
        count <= RESERVOIR, a uniform estimate beyond)."""
        if not self._res:
            return [0.0 for _ in qs]
        s = sorted(self._res)
        n = len(s)
        return [s[min(n - 1, max(0, int(q * n)))] for q in qs]


class StatsdSink:
    """Plain statsd line protocol over UDP (lib/telemetry.go statsd_addr).
    The plain protocol has no label dialect — labels are dropped, like
    go-metrics' statsd sink flattening."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def emit(self, kind: str, name: str, value: float,
             labels: LabelKey = ()) -> None:
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        try:
            self.sock.sendto(f"{name}:{value}|{suffix}".encode(), self.addr)
        except OSError:
            pass


class DogstatsdSink(StatsdSink):
    """Datadog's statsd dialect: the same line protocol plus |#tags
    (lib/telemetry.go dogstatsd_addr / dogstatsd_tags).  Per-metric
    labels append after the configured global tags."""

    def __init__(self, addr: str, tags: Optional[List[str]] = None):
        super().__init__(addr)
        self._tags = list(tags) if tags else []

    def emit(self, kind: str, name: str, value: float,
             labels: LabelKey = ()) -> None:
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        tags = self._tags + [f"{k}:{v}" for k, v in labels]
        tail = ("|#" + ",".join(tags)) if tags else ""
        try:
            self.sock.sendto(
                f"{name}:{value}|{suffix}{tail}".encode(),
                self.addr)
        except OSError:
            pass


class StatsiteSink:
    """statsite speaks the statsd line protocol over TCP
    (lib/telemetry.go statsite_addr).  Lines flush through a bounded
    queue + background writer so metric EMISSION never blocks the hot
    path on an unreachable collector (go-metrics' statsite sink
    buffers through a channel the same way); overflow drops lines.

    A sendall failure mid-line does NOT lose the line: the writer
    redials and retries once, then requeues it (dropping only if the
    queue is full) — a collector restart costs reordering, not data."""

    _QUEUE_CAP = 4096

    def __init__(self, addr: str):
        import queue as _queue
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self._q: "_queue.Queue[bytes]" = _queue.Queue(self._QUEUE_CAP)
        self._sock: Optional[socket.socket] = None
        threading.Thread(target=self._flush_loop, daemon=True).start()

    def emit(self, kind: str, name: str, value: float,
             labels: LabelKey = ()) -> None:
        import queue as _queue
        suffix = {"counter": "c", "gauge": "g", "sample": "ms"}[kind]
        try:
            self._q.put_nowait(f"{name}:{value}|{suffix}\n".encode())
        except _queue.Full:
            pass                      # collector down: shed, don't stall

    def _try_send(self, line: bytes) -> bool:
        try:
            if self._sock is None:
                self._sock = socket.create_connection(self.addr,
                                                      timeout=1.0)
            self._sock.sendall(line)
            return True
        except OSError:
            try:
                if self._sock is not None:
                    self._sock.close()
            finally:
                self._sock = None
            return False

    def _flush_loop(self) -> None:
        import queue as _queue
        import time as _time
        while True:
            line = self._q.get()
            if self._try_send(line):
                continue
            # redial once: a collector restart between lines shows up
            # as exactly one failed sendall on the stale socket
            if self._try_send(line):
                continue
            # still down: requeue the in-flight line so it survives the
            # outage (tail position — statsd lines are independent),
            # then back off before the next dial
            try:
                self._q.put_nowait(line)
            except _queue.Full:
                pass
            _time.sleep(0.5)


class Registry:
    def __init__(self, prefix: str = "consul"):
        self.prefix = prefix
        self._lock = threading.Lock()
        # keyed by (full_name, labels) — the go-metrics flattened key
        self._counters: Dict[Tuple[str, LabelKey], float] = \
            defaultdict(float)
        self._gauges: Dict[Tuple[str, LabelKey], float] = {}
        self._samples: Dict[Tuple[str, LabelKey], _Sample] = {}
        self._sinks: List[StatsdSink] = []

    def add_statsd_sink(self, addr: str) -> None:
        self._sinks.append(StatsdSink(addr))

    def add_dogstatsd_sink(self, addr: str,
                           tags: Optional[List[str]] = None) -> None:
        self._sinks.append(DogstatsdSink(addr, tags))

    def add_statsite_sink(self, addr: str) -> None:
        self._sinks.append(StatsiteSink(addr))

    def _name(self, parts) -> str:
        if isinstance(parts, str):
            return f"{self.prefix}.{parts}"
        return ".".join([self.prefix, *parts])

    def incr_counter(self, name, value: float = 1.0, labels=None) -> None:
        n = self._name(name)
        lk = _labels_key(labels)
        with self._lock:
            self._counters[(n, lk)] += value
        for s in self._sinks:
            s.emit("counter", n, value, lk)

    def set_gauge(self, name, value: float, labels=None) -> None:
        n = self._name(name)
        lk = _labels_key(labels)
        with self._lock:
            self._gauges[(n, lk)] = value
        for s in self._sinks:
            s.emit("gauge", n, value, lk)

    def add_sample(self, name, value: float, labels=None) -> None:
        n = self._name(name)
        lk = _labels_key(labels)
        with self._lock:
            self._samples.setdefault((n, lk), _Sample()).add(value)
        for s in self._sinks:
            s.emit("sample", n, value * 1000.0, lk)

    def measure_since(self, name, t0: float, labels=None) -> None:
        self.add_sample(name, time.perf_counter() - t0, labels=labels)

    # ---------------------------------------------------------------- dump

    @staticmethod
    def _finite(v: float) -> float:
        """JSON-safe: json.dumps of Infinity/NaN is invalid JSON for
        every spec-compliant consumer (allow_nan defaults on, but the
        output breaks jq/browsers); clamp degenerate aggregates."""
        return v if v == v and abs(v) != float("inf") else 0.0

    def dump(self) -> dict:
        """/v1/agent/metrics shape (agent/agent_endpoint.go
        AgentMetrics).  Unlabeled entries keep the classic two-key
        shape; labeled entries add a "Labels" object (the go-metrics
        DisplayMetrics Labels field).  Samples carry the reservoir
        quantiles alongside the aggregate."""

        def ent(k: Tuple[str, LabelKey], **fields) -> dict:
            d = {"Name": k[0], **fields}
            if k[1]:
                d["Labels"] = dict(k[1])
            return d

        with self._lock:
            samples = []
            for k, s in sorted(self._samples.items()):
                p50, p90, p99 = s.quantiles()
                samples.append(ent(
                    k, Count=s.count,
                    Sum=round(self._finite(s.total), 6),
                    Min=round(self._finite(s.min), 6),
                    Max=round(self._finite(s.max), 6),
                    Mean=round(self._finite(s.total / s.count)
                               if s.count else 0.0, 6),
                    P50=round(self._finite(p50), 6),
                    P90=round(self._finite(p90), 6),
                    P99=round(self._finite(p99), 6)))
            return {
                "Timestamp": time.strftime("%Y-%m-%d %H:%M:%S +0000",
                                           time.gmtime()),
                "Gauges": [ent(k, Value=v)
                           for k, v in sorted(self._gauges.items())],
                "Counters": [ent(k, Count=v)
                             for k, v in sorted(self._counters.items())],
                "Samples": samples,
            }

    # ---------------------------------------------------------- prometheus

    @staticmethod
    def _sanitize(n: str) -> str:
        return "".join(c if c.isalnum() or c == "_" else "_" for c in n)

    def _expo_names(self, kinds_names: Iterable[Tuple[str, str]],
                    reserve: Iterable[str] = ()
                    ) -> Dict[Tuple[str, str], str]:
        """Deterministic collision-free exposition names, keyed by
        (kind, name).

        Sanitizing '.'/'-' to '_' can map two distinct metric names to
        one exposition name (consul.rpc.cross-dc vs consul.rpc.cross_dc),
        and one raw name registered as two kinds collides with itself —
        either way duplicate `# TYPE` blocks are invalid exposition.
        The first entry in sorted order keeps the plain sanitized form;
        later colliders get a stable crc32 suffix (of the name for a
        name collision, of kind:name for a cross-kind one).  The
        allocation is deterministic for a given live metric set — a
        late-registering collider that sorts earlier will claim the
        plain name on the NEXT scrape (restart-stable beats within-run
        stable; colliding names are a bug `tools/metrics_audit.py`
        exists to catch).

        `reserve`: exposition names claimed out-of-band (a summary's
        _sum/_count/_min/_max companions) — a real metric landing on
        one gets suffixed instead of splitting the companion series."""
        out: Dict[Tuple[str, str], str] = {}
        taken: Dict[str, Tuple[str, str]] = {
            r: ("#reserved", r) for r in reserve}
        for kind, name in sorted(set(kinds_names),
                                 key=lambda kn: (kn[1], kn[0])):
            san = self._sanitize(name)
            if san in taken and taken[san] != (kind, name):
                tag = name if taken[san][1] != name else f"{kind}:{name}"
                san = f"{san}_{zlib.crc32(tag.encode()) & 0xFFFFFFFF:08x}"
            taken.setdefault(san, (kind, name))
            out[(kind, name)] = san
        return out

    @staticmethod
    def _labels_expo(lk: LabelKey, extra: Tuple[Tuple[str, str], ...] = ()
                     ) -> str:
        pairs = lk + extra
        if not pairs:
            return ""
        body = ",".join(
            '%s="%s"' % (Registry._sanitize(k),
                         v.replace("\\", "\\\\").replace('"', '\\"'))
            for k, v in pairs)
        return "{" + body + "}"

    def prometheus(self, extra_gauges: Optional[Dict[str, float]] = None
                   ) -> str:
        """Prometheus text exposition (the PrometheusOpts role,
        lib/telemetry.go:200; served at /v1/agent/metrics
        ?format=prometheus like the reference's agent_endpoint.go
        AgentMetrics prometheus handler).

        Names sanitize '.'/'-' to '_' with deterministic collision
        suffixes (one `# TYPE` block per exposition name); labels render
        as {k="v"}; samples expose the full summary shape —
        _sum/_count plus quantile series and min/max gauges.

        `extra_gauges` ({full raw name: value}) are live values the
        endpoint computes per scrape (sim tick, catalog index, member
        summary) WITHOUT mutating the shared registry; they ride the
        same sanitize-dedupe allocation as registered series, so the
        text and JSON forms expose identical families."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            samples = {k: (s.count, s.total, s.min, s.max, s.quantiles())
                       for k, s in self._samples.items()}
        for name, v in (extra_gauges or {}).items():
            gauges.setdefault((name, ()), float(v))

        # min/max companions (the in-memory sink's extra aggregate),
        # keyed by their OWNING sample — exposition names derive from
        # the summary's allocation below
        mins: Dict[Tuple[str, LabelKey], float] = {}
        maxs: Dict[Tuple[str, LabelKey], float] = {}
        for k, (count, _, mn, mx, _) in samples.items():
            if count:
                mins[k] = mn
                maxs[k] = mx

        # one namespace across kinds: a counter and a gauge landing on
        # the same exposition name is a collision too (even when the
        # raw metric names are identical).  Reserve every summary's
        # companion names (_sum/_count data lines, _min/_max gauges) so
        # a real metric that sanitizes onto one gets suffixed instead
        # of emitting a duplicate/conflicting TYPE block.
        reserve = [self._sanitize(k[0]) + suffix
                   for k in samples
                   for suffix in ("_sum", "_count", "_min", "_max")]
        expo = self._expo_names(
            [("counter", k[0]) for k in counters]
            + [("gauge", k[0]) for k in gauges]
            + [("summary", k[0]) for k in samples],
            reserve=reserve)

        out = []

        def series(kind: str, data: dict, fmt) -> None:
            by_name: Dict[str, list] = defaultdict(list)
            for (name, lk), v in data.items():
                by_name[expo[(kind, name)]].append((lk, v))
            for n in sorted(by_name):
                out.append(f"# TYPE {n} {kind}")
                for lk, v in sorted(by_name[n]):
                    fmt(n, lk, v)

        series("counter", counters,
               lambda n, lk, v: out.append(
                   f"{n}{self._labels_expo(lk)} {v:g}"))
        series("gauge", gauges,
               lambda n, lk, v: out.append(
                   f"{n}{self._labels_expo(lk)} {v:g}"))

        def fmt_sample(n, lk, v):
            count, total, mn, mx, (p50, p90, p99) = v
            for q, qv in (("0.5", p50), ("0.9", p90), ("0.99", p99)):
                out.append(f"{n}{self._labels_expo(lk, (('quantile', q),))}"
                           f" {qv:g}")
            out.append(f"{n}_sum{self._labels_expo(lk)} {total:g}")
            out.append(f"{n}_count{self._labels_expo(lk)} {count}")

        series("summary", samples, fmt_sample)
        for suffix, table in (("_min", mins), ("_max", maxs)):
            by_name: Dict[str, list] = defaultdict(list)
            for (name, lk), v in table.items():
                by_name[expo[("summary", name)] + suffix].append((lk, v))
            for n in sorted(by_name):
                out.append(f"# TYPE {n} gauge")
                for lk, v in sorted(by_name[n]):
                    out.append(f"{n}{self._labels_expo(lk)} {v:g}")
        return "\n".join(out) + "\n"


# process-wide default registry (go-metrics global pattern)
_default = Registry()


def default_registry() -> Registry:
    return _default


def incr_counter(name, value: float = 1.0, labels=None) -> None:
    _default.incr_counter(name, value, labels=labels)


def set_gauge(name, value: float, labels=None) -> None:
    _default.set_gauge(name, value, labels=labels)


def add_sample(name, value: float, labels=None) -> None:
    _default.add_sample(name, value, labels=labels)


def measure_since(name, t0: float, labels=None) -> None:
    _default.measure_since(name, t0, labels=labels)
