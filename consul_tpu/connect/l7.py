"""L7 route table: compiled discovery chain → executable routes.

One normalized route table serves BOTH consumers of a compiled chain:

  * consul_tpu/xds.py turns it into envoy.config.route.v3
    RouteConfiguration resources (the reference's
    agent/xds/routes.go:248 makeUpstreamRouteForDiscoveryChain), and
  * the built-in HTTP sidecar mode (connect/proxy.py
    HttpUpstreamListener) EVALUATES it per request, so splitters and
    routers move real traffic with no Envoy in the picture.

Keeping the two consumers on one table means the golden-tested xDS
output and the behavior-tested Python data plane cannot drift apart:
they are projections of the same structure.

Weights follow the envoy convention the reference uses: config-entry
weights are percentages with 0.01 granularity, scaled ×100 into a
10000-total weighted cluster (routes.go makeRouteActionForSplitter).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple


def _parse_duration(s) -> float:
    if not s:
        return 0.0
    if isinstance(s, (int, float)):
        return float(s)
    s = str(s)
    mult = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    for suffix, m in mult.items():
        if s.endswith(suffix) and s[:-len(suffix)].replace(
                ".", "", 1).isdigit():
            return float(s[:-len(suffix)]) * m
    try:
        return float(s)
    except ValueError:
        return 0.0


def _resolve_to_resolver(chain: dict, node_id: str) -> Optional[dict]:
    """Follow redirect indirection until a concrete resolver node."""
    seen = set()
    while node_id and node_id not in seen:
        seen.add(node_id)
        node = chain["Nodes"].get(node_id)
        if node is None:
            return None
        if node.get("Type") != "resolver":
            return node            # splitter/router handled by caller
        if node.get("Resolver"):   # redirect pointer
            node_id = node["Resolver"]
            continue
        return node
    return None


def _clusters_for_node(chain: dict, node_id: str) -> List[Tuple[int, str]]:
    """(weight, target_id) legs for the node a route lands on: a
    resolver is a single 10000-weight leg, a splitter its scaled
    legs."""
    node = _resolve_to_resolver(chain, node_id)
    if node is None:
        return []
    if node.get("Type") == "resolver":
        return [(10000, node["Target"])]
    if node.get("Type") == "splitter":
        legs = []
        for leg in node.get("Splits") or []:
            res = _resolve_to_resolver(chain, leg["Node"])
            if res is None or res.get("Type") != "resolver":
                continue
            legs.append((int(round(float(leg["Weight"]) * 100)),
                         res["Target"]))
        return legs
    return []


def _lb_for_node(chain: dict, node_id: str) -> Optional[dict]:
    """The landing resolver's LoadBalancer policy.  A splitter's legs
    must AGREE on the policy for it to apply to the (single) route
    action — the reference rejects divergent-leg LB at config-entry
    validation; since local entries aren't validated that way here,
    divergence resolves to NO policy rather than silently hashing one
    leg's share under another leg's rules."""
    node = _resolve_to_resolver(chain, node_id)
    if node is None:
        return None
    if node.get("Type") == "splitter":
        lbs = []
        for leg in node.get("Splits") or []:
            res = _resolve_to_resolver(chain, leg["Node"])
            if res is None:
                return None
            lbs.append(res.get("LoadBalancer") or None)
        if not lbs or any(lb != lbs[0] for lb in lbs):
            return None
        return lbs[0]
    return node.get("LoadBalancer") or None


def route_table(chain: dict) -> List[dict]:
    """Normalized route list, evaluated (and emitted) in order:
    [{"match": <chain Match dict>, "clusters": [(weight, target_id)],
      "prefix_rewrite": str, "timeout": float seconds, "retry": dict,
      "lb": <resolver LoadBalancer dict or None>}].
    """
    start = chain["Nodes"].get(chain.get("StartNode", ""))
    if start is None:
        return []
    out = []
    if start["Type"] == "router":
        for r in start.get("Routes") or []:
            dest = r.get("Destination") or {}
            retry = {}
            if dest.get("NumRetries"):
                retry["num_retries"] = int(dest["NumRetries"])
            if dest.get("RetryOnConnectFailure"):
                retry["on_connect_failure"] = True
            if dest.get("RetryOnStatusCodes"):
                retry["on_status_codes"] = list(dest["RetryOnStatusCodes"])
            out.append({
                "match": r.get("Match") or {"PathPrefix": "/"},
                "clusters": _clusters_for_node(chain, r["Node"]),
                "prefix_rewrite": dest.get("PrefixRewrite", ""),
                "timeout": _parse_duration(dest.get("RequestTimeout")),
                "retry": retry,
                "lb": _lb_for_node(chain, r["Node"]),
            })
    else:
        out.append({
            "match": {"PathPrefix": "/"},
            "clusters": _clusters_for_node(chain, chain["StartNode"]),
            "prefix_rewrite": "", "timeout": 0.0, "retry": {},
            "lb": _lb_for_node(chain, chain["StartNode"]),
        })
    return out


# --------------------------------------------------------------------------
# request evaluation (the HttpUpstreamListener side; semantics mirror
# envoy RouteMatch so the Python data plane behaves like the emitted
# xDS config would under a real Envoy)
# --------------------------------------------------------------------------

def _header_matches(m: dict, headers: Dict[str, str]) -> bool:
    val = headers.get(m.get("Name", "").lower())
    if m.get("Present"):
        got = val is not None
    elif m.get("Exact"):
        got = val == m["Exact"]
    elif m.get("Prefix"):
        got = val is not None and val.startswith(m["Prefix"])
    elif m.get("Suffix"):
        got = val is not None and val.endswith(m["Suffix"])
    elif m.get("Regex"):
        got = val is not None and re.fullmatch(m["Regex"], val) is not None
    else:
        return True
    return (not got) if m.get("Invert") else got


def _query_matches(m: dict, query: Dict[str, str]) -> bool:
    val = query.get(m.get("Name", ""))
    if m.get("Present"):
        return val is not None
    if m.get("Exact"):
        return val == m["Exact"]
    if m.get("Regex"):
        return val is not None and re.fullmatch(m["Regex"], val) is not None
    return True


def match_request(match: dict, method: str, path: str,
                  headers: Dict[str, str],
                  query: Dict[str, str]) -> bool:
    """Does one chain Match accept this request?  `headers` keys must
    be lower-cased by the caller; `path` excludes the query string."""
    if match.get("PathExact"):
        if path != match["PathExact"]:
            return False
    elif match.get("PathPrefix"):
        if not path.startswith(match["PathPrefix"]):
            return False
    elif match.get("PathRegex"):
        if re.fullmatch(match["PathRegex"], path) is None:
            return False
    methods = match.get("Methods") or []
    if methods and method.upper() not in [m.upper() for m in methods]:
        return False
    for hm in match.get("Header") or []:
        if not _header_matches(hm, headers):
            return False
    for qm in match.get("QueryParam") or []:
        if not _query_matches(qm, query):
            return False
    return True


def select_route(table: List[dict], method: str, path: str,
                 headers: Dict[str, str],
                 query: Dict[str, str]) -> Optional[dict]:
    for route in table:
        if match_request(route["match"], method, path, headers, query):
            return route
    return None


def hash_key(lb: Optional[dict], method: str, path: str,
             headers: Dict[str, str], query: Dict[str, str],
             peer_ip: str) -> Optional[str]:
    """The request's sticky-hash key under a ring_hash/maglev
    LoadBalancer's hash policies, or None when hashing does not apply
    (no LB, non-hash policy, or nothing matched).  Policies evaluate
    in order and combine; a `terminal` policy that produced a value
    short-circuits — envoy's HashPolicy semantics, which the emitted
    RDS config asks a real Envoy to apply identically."""
    if not lb or str(lb.get("policy", "")).lower() not in (
            "ring_hash", "maglev"):
        return None
    parts = []
    for hp in lb.get("hash_policies") or []:
        val = None
        if hp.get("source_ip"):
            val = peer_ip
        else:
            field = str(hp.get("field", "")).lower()
            name = hp.get("field_value", "")
            if field == "header":
                val = headers.get(name.lower())
            elif field == "query_parameter":
                val = query.get(name)
            elif field == "cookie":
                cookies = headers.get("cookie", "")
                for part in cookies.split(";"):
                    k, _, v = part.strip().partition("=")
                    if k == name:
                        val = v
                        break
        if val is not None:
            parts.append(val)
            if hp.get("terminal"):
                break
    return "|".join(parts) if parts else None


def pick_endpoint(eps: List, key: Optional[str]) -> List:
    """Order candidate endpoints for a request: hashed requests get a
    rendezvous-hash order (same key → same endpoint, minimal movement
    when the endpoint set changes), unhashed requests keep the list
    order.  Returns the FULL ordered list so connect failures fall
    through to the next choice."""
    if key is None or len(eps) <= 1:
        return list(eps)
    import hashlib

    def score(e):
        return hashlib.sha256(
            f"{key}|{e}".encode()).digest()

    return sorted(eps, key=score, reverse=True)


def pick_cluster(clusters: List[Tuple[int, str]],
                 roll: float) -> Optional[str]:
    """Weighted pick; `roll` ∈ [0,1) comes from the caller's RNG so
    tests can seed it."""
    total = sum(w for w, _ in clusters)
    if total <= 0:
        return clusters[0][1] if clusters else None
    point = roll * total
    acc = 0.0
    for w, target in clusters:
        acc += w
        if point < acc:
            return target
    return clusters[-1][1]


def strip_hop_headers(header_lines: List[str],
                      connection_value: str) -> List[str]:
    """Drop hop-by-hop headers before forwarding (RFC 7230 §6.1):
    `Connection` itself, every header its token list NOMINATES for
    this hop, and `Keep-Alive` whether nominated or not — a forwarded
    `Connection: keep-alive, x-foo` must not leak X-Foo upstream as if
    it were end-to-end (ADVICE r5).  End-to-end headers pass through
    untouched; the relay appends its own Connection header after."""
    drop = {t.strip().lower()
            for t in (connection_value or "").split(",") if t.strip()}
    drop |= {"connection", "keep-alive"}
    return [ln for ln in header_lines if ln
            and ln.partition(":")[0].strip().lower() not in drop]


def parse_http_head(head: bytes):
    """Parse an HTTP/1.1 request head into (method, path, qs, headers,
    query, proto), or None on a malformed request line.  Repeated
    field lines combine as a comma list (RFC 7230 §3.2.2) — last-wins
    would let tokens nominated by an EARLIER Connection header dodge
    strip_hop_headers.  Lives here (not the TLS-heavy proxy module) so
    the parsing rules unit-test anywhere."""
    try:
        text = head.decode("latin-1")
        request_line, _, rest = text.partition("\r\n")
        method, full_path, proto = request_line.split(" ", 2)
        headers: Dict[str, str] = {}
        for line in rest.split("\r\n"):
            if not line:
                continue
            k, _, v = line.partition(":")
            k = k.strip().lower()
            if k in headers:
                headers[k] = f"{headers[k]}, {v.strip()}"
            else:
                headers[k] = v.strip()
        path, _, qs = full_path.partition("?")
        query: Dict[str, str] = {}
        for pair in qs.split("&"):
            if pair:
                k, _, v = pair.partition("=")
                query[k] = v
        return method, path, qs, headers, query, proto
    except ValueError:
        return None
