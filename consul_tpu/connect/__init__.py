"""Connect service mesh: intentions + certificate authority.

Reference pillars: intention graph (agent/consul/intention_endpoint.go:73),
authorize path (agent/agent_endpoint.go AgentConnectAuthorize), CA
provider interface (agent/connect/ca/provider.go:58) with root rotation
(agent/consul/leader_connect_ca.go:53 CAManager).

CA classes are lazy exports: intentions need no crypto, and the
`cryptography` import must not tax (or break) intention-only paths.
"""

from consul_tpu.connect.intentions import (  # noqa: F401
    ALLOW, DENY, authorize, match_order, precedence,
)


def __getattr__(name):
    if name in ("BuiltinCA", "CAManager"):
        from consul_tpu.connect import ca
        return getattr(ca, name)
    raise AttributeError(name)
