"""Intention evaluation: source→destination L4 authorization graph.

Reference semantics (agent/consul/intention_endpoint.go:73 Apply/Match/
Check; precedence agent/structs/intention.go UpdatePrecedence): an
intention names a source and destination service (either may be the
wildcard "*") with an allow/deny action.  Matching orders candidates by
precedence — exact beats wildcard, destination side weighs highest — and
the FIRST match decides; with no match the ACL default policy applies
(intention deny-by-default iff acl default deny).
"""

from __future__ import annotations

from typing import List, Optional

ALLOW = "allow"
DENY = "deny"
WILDCARD = "*"


def precedence(source: str, destination: str) -> int:
    """structs.Intention precedence values: exact/exact=9, */exact=8,
    exact/*=6, */*=5 (destination specificity dominates)."""
    src_exact = source != WILDCARD
    dst_exact = destination != WILDCARD
    if dst_exact and src_exact:
        return 9
    if dst_exact:
        return 8
    if src_exact:
        return 6
    return 5


def _matches(pattern: str, name: str) -> bool:
    return pattern == WILDCARD or pattern == name


def match_order(intentions: List[dict], name: str,
                by: str = "destination") -> List[dict]:
    """Intentions whose `by` side matches `name`, highest precedence
    first (IntentionMatch ordering)."""
    hits = [i for i in intentions if _matches(i[by], name)]
    return sorted(hits, key=lambda i: (-i["precedence"],
                                       i["destination"], i["source"]))


def authorize(intentions: List[dict], source: str, destination: str,
              default_allow: bool) -> tuple[bool, str]:
    """(authorized, reason) for a source→destination connection
    (ConnectAuthorize / Intention.Check)."""
    for i in sorted(intentions, key=lambda x: -x["precedence"]):
        if _matches(i["source"], source) \
                and _matches(i["destination"], destination):
            ok = i["action"] == ALLOW
            return ok, (f"Matched intention {i['source']}=>"
                        f"{i['destination']} action={i['action']}")
    if default_allow:
        return True, "Default behavior (ACL allow)"
    return False, "Default behavior (ACL deny): no matching intention"


def spiffe_service(uri: str) -> Optional[str]:
    """Extract the service name from a SPIFFE URI
    (spiffe://<domain>/ns/<ns>/dc/<dc>/svc/<service> — connect/spiffe)."""
    if not uri.startswith("spiffe://"):
        return None
    parts = uri.split("/")
    try:
        return parts[parts.index("svc") + 1]
    except (ValueError, IndexError):
        return None
