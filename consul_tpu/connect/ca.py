"""Connect certificate authority: builtin provider + rotation manager.

The reference's CA stack: a pluggable Provider interface
(agent/connect/ca/provider.go:58 — builtin "consul" provider generates
and stores its own root), leaf signing with URI SANs carrying SPIFFE ids
(connect/), and a CAManager on the leader driving root generation and
rotation with the old root kept in the trust bundle until its leaves age
out (agent/consul/leader_connect_ca.go:53).

Real X.509 via `cryptography`: EC P-256 keys, self-signed roots, leaf
certs with spiffe:// URI SANs.  CA state (roots + active id) serializes
to a plain dict so it can replicate through the FSM like the reference's
raft-backed CA tables.
"""

from __future__ import annotations

import datetime
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

_BACKDATE = datetime.timedelta(minutes=5)   # clock-skew allowance


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class BuiltinCA:
    """The builtin ("consul") CA provider: one EC root, leaf signing."""

    def __init__(self, trust_domain: str, dc: str = "dc1",
                 root_ttl_days: int = 3650, leaf_ttl_hours: int = 72,
                 serial: int = 1,
                 key_pem: Optional[str] = None,
                 cert_pem: Optional[str] = None):
        self.trust_domain = trust_domain
        self.dc = dc
        self.leaf_ttl_hours = leaf_ttl_hours
        self.id = f"root-{serial}"
        if (key_pem is None) != (cert_pem is None):
            # a cert without its key (or vice versa) silently regenerating
            # a surprise CA is the worst failure mode — refuse loudly
            raise ValueError("CA cert and key must be supplied together")
        if key_pem is None:
            self._key = ec.generate_private_key(ec.SECP256R1())
            subject = x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME,
                                   f"Consul CA {serial}"),
            ])
            now = _utcnow()
            self._cert = (
                x509.CertificateBuilder()
                .subject_name(subject).issuer_name(subject)
                .public_key(self._key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _BACKDATE)
                .not_valid_after(now + datetime.timedelta(
                    days=root_ttl_days))
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=0),
                               critical=True)
                .add_extension(x509.SubjectAlternativeName([
                    x509.UniformResourceIdentifier(
                        f"spiffe://{trust_domain}")]),
                    critical=False)
                .sign(self._key, hashes.SHA256())
            )
        else:
            self._key = serialization.load_pem_private_key(
                key_pem.encode(), password=None)
            self._cert = x509.load_pem_x509_certificate(cert_pem.encode())

    # -------------------------------------------------------------- pems

    @property
    def cert_pem(self) -> str:
        return self._cert.public_bytes(
            serialization.Encoding.PEM).decode()

    @property
    def key_pem(self) -> str:
        return self._key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode()

    # ------------------------------------------------------------ signing

    def spiffe_id(self, service: str) -> str:
        return (f"spiffe://{self.trust_domain}/ns/default/dc/{self.dc}"
                f"/svc/{service}")

    def sign(self, common_name: str, sans: list,
             ttl: datetime.timedelta) -> Tuple[str, str]:
        """Generic end-entity signing: ONE X.509 builder for every
        caller (service leaves, agent/server TLS certs) so extensions
        and key handling cannot drift between them."""
        key = ec.generate_private_key(ec.SECP256R1())
        now = _utcnow()
        cert = (
            x509.CertificateBuilder()
            .subject_name(x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
            .issuer_name(self._cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _BACKDATE)
            .not_valid_after(now + ttl)
            .add_extension(x509.SubjectAlternativeName(sans),
                           critical=False)
            .add_extension(x509.BasicConstraints(ca=False,
                                                 path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_encipherment=True,
                content_commitment=False, data_encipherment=False,
                key_agreement=False, key_cert_sign=False, crl_sign=False,
                encipher_only=False, decipher_only=False), critical=True)
            .sign(self._key, hashes.SHA256())
        )
        return (cert.public_bytes(serialization.Encoding.PEM).decode(),
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption()).decode())

    def sign_leaf(self, service: str) -> Tuple[str, str]:
        """(cert_pem, key_pem) for a service leaf with a SPIFFE URI SAN
        (provider.go Sign; leaf shape connect/)."""
        return self.sign(
            service,
            [x509.UniformResourceIdentifier(self.spiffe_id(service))],
            datetime.timedelta(hours=self.leaf_ttl_hours))

    def verify_leaf(self, cert_pem: str) -> bool:
        """Does this leaf chain to our root (signature + validity)?"""
        leaf = x509.load_pem_x509_certificate(cert_pem.encode())
        try:
            leaf.verify_directly_issued_by(self._cert)
        except Exception:
            return False
        now = _utcnow()
        return (leaf.not_valid_before_utc <= now
                <= leaf.not_valid_after_utc)


class CAManager:
    """Root lifecycle on the leader (leader_connect_ca.go:53): initialize,
    sign leaves under the ACTIVE root, rotate keeping the old root in the
    trust bundle so in-flight leaves stay verifiable."""

    def __init__(self, trust_domain: Optional[str] = None, dc: str = "dc1",
                 leaf_ttl_hours: int = 72):
        self.trust_domain = trust_domain or \
            f"{uuid.uuid4()}.consul"
        self.dc = dc
        self.leaf_ttl_hours = leaf_ttl_hours
        self._lock = threading.Lock()
        self._serial = 1
        self._roots: List[BuiltinCA] = [
            BuiltinCA(self.trust_domain, dc, serial=1,
                      leaf_ttl_hours=leaf_ttl_hours)]

    # -------------------------------------------------------------- roots

    @property
    def active(self) -> BuiltinCA:
        with self._lock:
            return self._roots[-1]

    def roots(self) -> List[dict]:
        """Trust bundle (GET /v1/connect/ca/roots shape)."""
        with self._lock:
            active_id = self._roots[-1].id
            return [{"ID": r.id, "Name": f"Consul CA {i + 1}",
                     "RootCert": r.cert_pem,
                     "Active": r.id == active_id}
                    for i, r in enumerate(self._roots)]

    def rotate(self) -> str:
        """Generate + activate a new root; prior roots stay in the bundle
        (rotation keeps old leaves verifiable — leader_connect_ca.go)."""
        with self._lock:
            self._serial += 1
            self._roots.append(BuiltinCA(self.trust_domain, self.dc,
                                         serial=self._serial,
                                         leaf_ttl_hours=self.leaf_ttl_hours))
            return self._roots[-1].id

    # ------------------------------------------------------------- leaves

    def sign_leaf(self, service: str) -> dict:
        ca = self.active
        cert, key = ca.sign_leaf(service)
        return {"SerialNumber": "", "CertPEM": cert, "PrivateKeyPEM": key,
                "Service": service,
                "ServiceURI": ca.spiffe_id(service)}

    def verify_leaf(self, cert_pem: str) -> bool:
        with self._lock:
            roots = list(self._roots)
        return any(r.verify_leaf(cert_pem) for r in roots)
