"""Connect certificate authority: pluggable providers + rotation manager.

The reference's CA stack: a pluggable Provider interface
(agent/connect/ca/provider.go:58) with a builtin "consul" provider that
generates its own root plus external providers (Vault
provider_vault.go, AWS ACM-PCA provider_aws.go) whose root material
comes from outside; leaf signing with URI SANs carrying SPIFFE ids
(connect/); a CAManager on the leader driving root generation and
rotation with the old root kept in the trust bundle until its leaves
age out (agent/consul/leader_connect_ca.go:53), CROSS-SIGNING the new
root with the old one during provider/root switches so in-flight
leaves validate through either path; and a leaf-CSR rate limiter
protecting the servers (agent/consul/server.go:148 csrRateLimiter).

Here: `CAProvider` is the interface; `BuiltinCA` self-generates
(the "consul" provider), `ExternalCA` wraps operator-supplied root
material (the Vault/ACM shape without egress — the secret key arrives
via config instead of a Vault read).  Real X.509 via `cryptography`:
EC P-256 keys, self-signed roots, leaf certs with spiffe:// URI SANs.
CA state serializes to a plain dict so it can replicate through the
FSM like the reference's raft-backed CA tables.
"""

from __future__ import annotations

import base64
import datetime
import json
import threading
import uuid
from typing import Dict, List, Optional, Tuple

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:                                 # pragma: no cover
    # the container may not ship `cryptography`; the mesh control
    # plane (proxycfg snapshots, xDS pushes, intentions→RBAC) must
    # still run, so a structurally-faithful stub provider takes over
    # (PEM-shaped blobs, issuer chains, validity windows — no real
    # crypto).  Anything needing true X.509 (external providers, JWT
    # auth-methods) raises at use, not at import.
    x509 = hashes = serialization = ec = NameOID = None
    HAVE_CRYPTOGRAPHY = False

_BACKDATE = datetime.timedelta(minutes=5)   # clock-skew allowance


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class CARateLimitError(Exception):
    """Leaf CSR rate exceeded (server.go:148 csrRateLimiter; callers
    surface 429)."""


class CAProvider:
    """The Provider interface (agent/connect/ca/provider.go:58).

    Concrete providers supply root material and signing; the manager
    owns rotation, cross-signing orchestration, bundles, and rate
    limits.  Required surface:

      name            class attr, the config `Provider` string
      id              active root id
      cert_pem        active root certificate
      trust_domain / dc / leaf_ttl_hours
      sign(common_name, sans, ttl) -> (cert_pem, key_pem)
      sign_leaf(service) -> (cert_pem, key_pem)
      verify_leaf(cert_pem) -> bool
      cross_sign(cert_pem) -> pem   (re-issue the given CA cert under
                                     OUR key: the bridge old→new roots
                                     ride during rotation)
      supports_cross_signing() -> bool
    """

    name = "abstract"

    def supports_cross_signing(self) -> bool:
        return True


class BuiltinCA(CAProvider):
    """The builtin ("consul") CA provider: one EC root, leaf signing."""

    name = "consul"

    def __init__(self, trust_domain: str, dc: str = "dc1",
                 root_ttl_days: int = 3650, leaf_ttl_hours: int = 72,
                 serial: int = 1,
                 key_pem: Optional[str] = None,
                 cert_pem: Optional[str] = None):
        self.trust_domain = trust_domain
        self.dc = dc
        self.leaf_ttl_hours = leaf_ttl_hours
        self.id = f"root-{serial}"
        if (key_pem is None) != (cert_pem is None):
            # a cert without its key (or vice versa) silently regenerating
            # a surprise CA is the worst failure mode — refuse loudly
            raise ValueError("CA cert and key must be supplied together")
        if key_pem is None:
            self._key = ec.generate_private_key(ec.SECP256R1())
            subject = x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME,
                                   f"Consul CA {serial}"),
            ])
            now = _utcnow()
            self._cert = (
                x509.CertificateBuilder()
                .subject_name(subject).issuer_name(subject)
                .public_key(self._key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _BACKDATE)
                .not_valid_after(now + datetime.timedelta(
                    days=root_ttl_days))
                # no pathLenConstraint: the root must be able to issue
                # the CA=true cross-signed bridge during rotation
                # (path_length=0 would make RFC 5280 validators reject
                # leaf -> bridge -> root chains)
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.SubjectAlternativeName([
                    x509.UniformResourceIdentifier(
                        f"spiffe://{trust_domain}")]),
                    critical=False)
                .sign(self._key, hashes.SHA256())
            )
        else:
            self._key = serialization.load_pem_private_key(
                key_pem.encode(), password=None)
            self._cert = x509.load_pem_x509_certificate(cert_pem.encode())

    # -------------------------------------------------------------- pems

    @property
    def cert_pem(self) -> str:
        return self._cert.public_bytes(
            serialization.Encoding.PEM).decode()

    @property
    def key_pem(self) -> str:
        return self._key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode()

    # ------------------------------------------------------------ signing

    def spiffe_id(self, service: str) -> str:
        return (f"spiffe://{self.trust_domain}/ns/default/dc/{self.dc}"
                f"/svc/{service}")

    def sign(self, common_name: str, sans: list,
             ttl: datetime.timedelta) -> Tuple[str, str]:
        """Generic end-entity signing: ONE X.509 builder for every
        caller (service leaves, agent/server TLS certs) so extensions
        and key handling cannot drift between them."""
        key = ec.generate_private_key(ec.SECP256R1())
        now = _utcnow()
        cert = (
            x509.CertificateBuilder()
            .subject_name(x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
            .issuer_name(self._cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _BACKDATE)
            .not_valid_after(now + ttl)
            .add_extension(x509.SubjectAlternativeName(sans),
                           critical=False)
            .add_extension(x509.BasicConstraints(ca=False,
                                                 path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_encipherment=True,
                content_commitment=False, data_encipherment=False,
                key_agreement=False, key_cert_sign=False, crl_sign=False,
                encipher_only=False, decipher_only=False), critical=True)
            .sign(self._key, hashes.SHA256())
        )
        return (cert.public_bytes(serialization.Encoding.PEM).decode(),
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption()).decode())

    def sign_leaf(self, service: str) -> Tuple[str, str]:
        """(cert_pem, key_pem) for a service leaf with a SPIFFE URI SAN
        (provider.go Sign; leaf shape connect/)."""
        return self.sign(
            service,
            [x509.UniformResourceIdentifier(self.spiffe_id(service))],
            datetime.timedelta(hours=self.leaf_ttl_hours))

    def verify_leaf(self, cert_pem: str) -> bool:
        """Does this leaf chain to our root (signature + validity)?"""
        leaf = x509.load_pem_x509_certificate(cert_pem.encode())
        try:
            leaf.verify_directly_issued_by(self._cert)
        except Exception:
            return False
        now = _utcnow()
        return (leaf.not_valid_before_utc <= now
                <= leaf.not_valid_after_utc)

    def cross_sign(self, cert_pem: str) -> str:
        """Re-issue another CA's certificate under OUR key (same
        subject + public key, issuer = us): trust in the old root
        transitively covers leaves of the new one during rotation
        (provider.go CrossSignCA)."""
        other = x509.load_pem_x509_certificate(cert_pem.encode())
        now = _utcnow()
        cross = (
            x509.CertificateBuilder()
            .subject_name(other.subject)
            .issuer_name(self._cert.subject)
            .public_key(other.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _BACKDATE)
            .not_valid_after(other.not_valid_after_utc)
            .add_extension(x509.BasicConstraints(ca=True,
                                                 path_length=0),
                           critical=True)
            .sign(self._key, hashes.SHA256())
        )
        return cross.public_bytes(serialization.Encoding.PEM).decode()


def _stub_pem(kind: str, payload: dict) -> str:
    """PEM-shaped wrapper over a JSON payload: base64 body between the
    canonical armor lines, so anything that greps for BEGIN/END
    markers or ships certs around as opaque strings keeps working."""
    body = base64.b64encode(
        json.dumps(payload, sort_keys=True).encode()).decode()
    lines = [body[i:i + 64] for i in range(0, len(body), 64)]
    return (f"-----BEGIN {kind}-----\n" + "\n".join(lines)
            + f"\n-----END {kind}-----\n")


def _stub_payload(pem: str) -> dict:
    body = "".join(ln for ln in pem.splitlines()
                   if ln and not ln.startswith("-----"))
    return json.loads(base64.b64decode(body))


class StubBuiltinCA(CAProvider):
    """`cryptography`-free builtin provider: the same surface as
    BuiltinCA with deterministic PEM-shaped blobs instead of X.509.
    Issuer chains, validity windows, SPIFFE URI SANs, and cross-signed
    bridges all behave structurally (verify_leaf checks issuer +
    window), which is what the proxycfg/xDS plane needs; only the
    bytes aren't real certificates."""

    name = "consul"

    def __init__(self, trust_domain: str, dc: str = "dc1",
                 root_ttl_days: int = 3650, leaf_ttl_hours: int = 72,
                 serial: int = 1,
                 key_pem: Optional[str] = None,
                 cert_pem: Optional[str] = None):
        if (key_pem is None) != (cert_pem is None):
            raise ValueError("CA cert and key must be supplied together")
        self.trust_domain = trust_domain
        self.dc = dc
        self.leaf_ttl_hours = leaf_ttl_hours
        self.id = f"root-{serial}"
        if cert_pem is not None:
            payload = _stub_payload(cert_pem)
            self._subject = payload["subject"]
            self._cert_payload = payload
            return
        now = _utcnow().timestamp()
        self._subject = f"Consul CA {serial} {uuid.uuid4().hex[:12]}"
        self._cert_payload = {
            "subject": self._subject, "issuer": self._subject,
            "serial": uuid.uuid4().hex, "ca": True,
            "not_before": now - _BACKDATE.total_seconds(),
            "not_after": now + root_ttl_days * 86400.0,
            "uris": [f"spiffe://{trust_domain}"],
        }

    @property
    def cert_pem(self) -> str:
        return _stub_pem("CERTIFICATE", self._cert_payload)

    @property
    def key_pem(self) -> str:
        return _stub_pem("PRIVATE KEY",
                         {"subject": self._subject, "stub": True})

    def spiffe_id(self, service: str) -> str:
        return (f"spiffe://{self.trust_domain}/ns/default/dc/{self.dc}"
                f"/svc/{service}")

    def sign(self, common_name: str, sans: list,
             ttl: datetime.timedelta) -> Tuple[str, str]:
        now = _utcnow().timestamp()
        cert = _stub_pem("CERTIFICATE", {
            "subject": common_name, "issuer": self._subject,
            "serial": uuid.uuid4().hex, "ca": False,
            "not_before": now - _BACKDATE.total_seconds(),
            "not_after": now + ttl.total_seconds(),
            "uris": [str(s) for s in sans],
        })
        key = _stub_pem("PRIVATE KEY",
                        {"subject": common_name, "stub": True})
        return cert, key

    def sign_leaf(self, service: str) -> Tuple[str, str]:
        return self.sign(
            service, [self.spiffe_id(service)],
            datetime.timedelta(hours=self.leaf_ttl_hours))

    def verify_leaf(self, cert_pem: str) -> bool:
        try:
            payload = _stub_payload(cert_pem)
        except Exception:
            return False
        now = _utcnow().timestamp()
        return (payload.get("issuer") == self._subject
                and payload.get("not_before", 0.0) <= now
                <= payload.get("not_after", 0.0))

    def cross_sign(self, cert_pem: str) -> str:
        other = _stub_payload(cert_pem)
        now = _utcnow().timestamp()
        return _stub_pem("CERTIFICATE", {
            "subject": other["subject"], "issuer": self._subject,
            "serial": uuid.uuid4().hex, "ca": True,
            "not_before": now - _BACKDATE.total_seconds(),
            "not_after": other.get("not_after", now),
            "uris": other.get("uris", []),
        })


def new_builtin_ca(*args, **kwargs) -> CAProvider:
    """The builtin provider for this interpreter: real X.509 when
    `cryptography` is importable, the structural stub otherwise."""
    cls = BuiltinCA if HAVE_CRYPTOGRAPHY else StubBuiltinCA
    return cls(*args, **kwargs)


class ExternalCA(BuiltinCA):
    """Operator-supplied root material (the Vault / ACM-PCA provider
    shape, provider_vault.go — minus the network fetch: in a no-egress
    environment the root cert+key arrive via the CA config instead of
    a Vault read).  Everything else (signing, verification,
    cross-signing) is the common X.509 machinery."""

    name = "external"

    def __init__(self, trust_domain: str, cert_pem: str, key_pem: str,
                 dc: str = "dc1", leaf_ttl_hours: int = 72,
                 serial: int = 1):
        if not cert_pem or not key_pem:
            raise ValueError(
                "external CA requires RootCert and PrivateKey")
        super().__init__(trust_domain, dc=dc,
                         leaf_ttl_hours=leaf_ttl_hours, serial=serial,
                         key_pem=key_pem, cert_pem=cert_pem)
        # fail at CONFIG time, not at the first mesh-wide handshake
        # failure: the key must actually match the certificate and the
        # certificate must be a CA
        if self._cert.public_key().public_numbers() != \
                self._key.public_key().public_numbers():
            raise ValueError(
                "external CA private key does not match RootCert")
        try:
            bc = self._cert.extensions.get_extension_for_class(
                x509.BasicConstraints).value
        except x509.ExtensionNotFound:
            raise ValueError("external RootCert has no "
                             "BasicConstraints extension")
        if not bc.ca:
            raise ValueError("external RootCert is not a CA "
                             "certificate")
        now = _utcnow()
        if not (self._cert.not_valid_before_utc <= now
                <= self._cert.not_valid_after_utc):
            raise ValueError("external RootCert is outside its "
                             "validity window")
        self.id = f"external-{serial}"


class CAManager:
    """Root lifecycle on the leader (leader_connect_ca.go:53): initialize,
    sign leaves under the ACTIVE root, rotate keeping the old root in the
    trust bundle so in-flight leaves stay verifiable."""

    def __init__(self, trust_domain: Optional[str] = None, dc: str = "dc1",
                 leaf_ttl_hours: int = 72,
                 csr_max_per_second: float = 50.0):
        self.trust_domain = trust_domain or \
            f"{uuid.uuid4()}.consul"
        self.dc = dc
        self.leaf_ttl_hours = leaf_ttl_hours
        self._lock = threading.Lock()
        self._serial = 1
        self._roots: List[CAProvider] = [
            new_builtin_ca(self.trust_domain, dc, serial=1,
                           leaf_ttl_hours=leaf_ttl_hours)]
        # cross-signed bridge certs per root id (rotation trust path)
        self._cross_signed: Dict[str, str] = {}
        # leaf-CSR token bucket (server.go:148 csrRateLimiter);
        # <= 0 disables
        self.csr_max_per_second = csr_max_per_second
        self._csr_tokens = csr_max_per_second
        self._csr_stamp = 0.0

    # -------------------------------------------------------------- roots

    @property
    def active(self) -> BuiltinCA:
        with self._lock:
            return self._roots[-1]

    def roots(self) -> List[dict]:
        """Trust bundle (GET /v1/connect/ca/roots shape); rotated-in
        roots carry the cross-signed bridge cert when one exists."""
        with self._lock:
            active_id = self._roots[-1].id
            out = []
            for i, r in enumerate(self._roots):
                row = {"ID": r.id, "Name": f"Consul CA {i + 1}",
                       "RootCert": r.cert_pem,
                       "Active": r.id == active_id}
                if r.id in self._cross_signed:
                    row["IntermediateCerts"] = [
                        self._cross_signed[r.id]]
                out.append(row)
            return out

    @property
    def provider_name(self) -> str:
        return self.active.name

    def rotate(self) -> str:
        """Generate + activate a new builtin root; prior roots stay in
        the bundle (rotation keeps old leaves verifiable —
        leader_connect_ca.go)."""
        with self._lock:
            self._serial += 1
            new = new_builtin_ca(self.trust_domain, self.dc,
                                 serial=self._serial,
                                 leaf_ttl_hours=self.leaf_ttl_hours)
            self._activate_locked(new)
            return new.id

    def set_provider(self, provider: str, config: dict) -> str:
        """Switch the active provider (PUT /v1/connect/ca/configuration
        — leader_connect_ca.go UpdateConfiguration): the outgoing
        active root cross-signs the incoming one when it can, so
        leaves already issued keep a trust path through either root
        until they age out."""
        with self._lock:
            self._serial += 1
            if provider in ("consul", "builtin"):
                new: CAProvider = new_builtin_ca(
                    self.trust_domain, self.dc, serial=self._serial,
                    leaf_ttl_hours=self.leaf_ttl_hours)
            elif provider == "external":
                if not HAVE_CRYPTOGRAPHY:
                    raise ValueError(
                        "external CA provider requires the "
                        "'cryptography' package")
                new = ExternalCA(
                    self.trust_domain,
                    cert_pem=config.get("RootCert", ""),
                    key_pem=config.get("PrivateKey", ""),
                    dc=self.dc, serial=self._serial,
                    leaf_ttl_hours=self.leaf_ttl_hours)
            else:
                raise ValueError(f"unknown CA provider {provider!r}")
            self._activate_locked(new)
            return new.id

    def _activate_locked(self, new: CAProvider) -> None:
        old = self._roots[-1]
        if old.supports_cross_signing():
            self._cross_signed[new.id] = old.cross_sign(new.cert_pem)
        self._roots.append(new)

    # ------------------------------------------------------------- leaves

    def _take_csr_token(self) -> None:
        """Token bucket refilled at csr_max_per_second; raises
        CARateLimitError when drained (server.go:148 — a leaf-signing
        stampede must not starve raft/rpc)."""
        import time as _time
        if self.csr_max_per_second <= 0:
            return
        now = _time.monotonic()
        rate = self.csr_max_per_second
        # burst floor of 1: fractional rates (0.5 = one per 2s) must
        # still accumulate a whole token, not block forever
        self._csr_tokens = min(
            max(rate, 1.0),
            self._csr_tokens + (now - self._csr_stamp) * rate)
        self._csr_stamp = now
        if self._csr_tokens < 1.0:
            raise CARateLimitError(
                "connect CSR rate limit exceeded "
                f"({rate:g}/s)")
        self._csr_tokens -= 1.0

    def sign_leaf(self, service: str) -> dict:
        with self._lock:
            self._take_csr_token()
            ca = self._roots[-1]
        cert, key = ca.sign_leaf(service)
        return {"SerialNumber": "", "CertPEM": cert, "PrivateKeyPEM": key,
                "Service": service,
                "ServiceURI": ca.spiffe_id(service)}

    def verify_leaf(self, cert_pem: str) -> bool:
        with self._lock:
            roots = list(self._roots)
        return any(r.verify_leaf(cert_pem) for r in roots)
